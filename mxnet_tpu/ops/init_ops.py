"""Creation ops: zeros/ones/full/arange/eye and random samplers.

Reference: src/operator/tensor/init_op.cc, src/operator/random/
(sample_op.cc multinomial_op.cc unique_sample_op.cc) and
include/mxnet/random_generator.h.

Random ops take an explicit PRNG ``key`` argument (pure functions); the
NDArray layer threads keys from the global/trace-scoped generator in
mxnet_tpu/random.py — the TPU-native replacement for the reference's
per-device RNG resource (src/resource.cc kRandom).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import np_dtype
from .registry import register


@register("_zeros", aliases=("zeros",))
def zeros(shape=(), dtype="float32", **_):
    """All-zeros array of `shape` (reference: _zeros, init_op.cc)."""
    return jnp.zeros(tuple(shape), dtype=np_dtype(dtype))


@register("_ones", aliases=("ones",))
def ones(shape=(), dtype="float32", **_):
    """All-ones array of `shape` (reference: _ones, init_op.cc)."""
    return jnp.ones(tuple(shape), dtype=np_dtype(dtype))


@register("_full", aliases=("full",))
def full(shape=(), value=0.0, dtype="float32", **_):
    """Array of `shape` filled with scalar `value` (reference: _full)."""
    return jnp.full(tuple(shape), value, dtype=np_dtype(dtype))


@register("zeros_like")
def zeros_like(x, **_):
    """Zeros with the shape/dtype of `x` (reference: zeros_like)."""
    return jnp.zeros_like(x)


@register("ones_like")
def ones_like(x, **_):
    """Ones with the shape/dtype of `x` (reference: ones_like)."""
    return jnp.ones_like(x)


@register("_arange", aliases=("arange",))
def arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32", **_):
    """Evenly spaced values in ``[start, stop)`` with `step`, each value
    repeated `repeat` times (reference: _arange, init_op.cc)."""
    out = jnp.arange(start, stop, step, dtype=np_dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, int(repeat))
    return out


@register("_linspace", aliases=("linspace",))
def linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32", **_):
    """`num` evenly spaced values from `start` to `stop`, endpoint
    included when `endpoint` (reference: _linspace)."""
    return jnp.linspace(start, stop, int(num), endpoint=bool(endpoint),
                        dtype=np_dtype(dtype))


@register("_eye", aliases=("eye",))
def eye(N=1, M=0, k=0, dtype="float32", **_):
    """Identity-like (N, M) matrix with ones on diagonal `k`
    (reference: _eye, init_op.cc)."""
    m = int(M) if M else int(N)
    return jnp.eye(int(N), m, k=int(k), dtype=np_dtype(dtype))


# ------------------------------------------------------------------- random

# All samplers: fn(key, [dist-param tensors...], shape=..., dtype=...)


def _check_param(op, name, value, ok):
    """Reject invalid SCALAR distribution parameters at dispatch, like
    the reference kernels' CHECK macros (src/operator/random/
    sample_op.h; surfaced there as a deferred engine error, here
    synchronously).  Array-valued params are validated nowhere cheap —
    same as feeding NaNs: garbage in, garbage out."""
    if isinstance(value, (int, float)) and not ok(value):
        from ..base import MXNetError

        raise MXNetError("%s: invalid %s=%r" % (op, name, value))


@register("_random_uniform", aliases=("random_uniform", "uniform"))
def random_uniform(key, low=0.0, high=1.0, shape=(1,), dtype="float32", **_):
    """Uniform samples over ``[low, high)`` of `shape`
    (reference: _random_uniform, sample_op.cc)."""
    d = np_dtype(dtype)
    return jax.random.uniform(key, tuple(shape), dtype=d, minval=low, maxval=high)


@register("_random_normal", aliases=("random_normal", "normal"))
def random_normal(key, loc=0.0, scale=1.0, shape=(1,), dtype="float32", **_):
    """Gaussian samples with mean `loc` and stddev `scale`
    (reference: _random_normal, sample_op.cc)."""
    _check_param("random_normal", "scale", scale, lambda v: v >= 0)
    d = np_dtype(dtype)
    return jax.random.normal(key, tuple(shape), dtype=d) * scale + loc


@register("_random_gamma", aliases=("random_gamma",))
def random_gamma(key, alpha=1.0, beta=1.0, shape=(1,), dtype="float32", **_):
    """Gamma samples with shape `alpha` and scale `beta`
    (reference: _random_gamma, sample_op.cc)."""
    _check_param("random_gamma", "alpha", alpha, lambda v: v > 0)
    _check_param("random_gamma", "beta", beta, lambda v: v > 0)
    d = np_dtype(dtype)
    return jax.random.gamma(key, alpha, tuple(shape), dtype=d) * beta


@register("_random_exponential", aliases=("random_exponential",))
def random_exponential(key, lam=1.0, shape=(1,), dtype="float32", **_):
    """Exponential samples with rate `lam`
    (reference: _random_exponential, sample_op.cc)."""
    _check_param("random_exponential", "lam", lam, lambda v: v > 0)
    d = np_dtype(dtype)
    return jax.random.exponential(key, tuple(shape), dtype=d) / lam


@register("_random_poisson", aliases=("random_poisson",))
def random_poisson(key, lam=1.0, shape=(1,), dtype="float32", **_):
    """Poisson counts with mean `lam`, cast to `dtype`
    (reference: _random_poisson, sample_op.cc)."""
    _check_param("random_poisson", "lam", lam, lambda v: v >= 0)
    out = jax.random.poisson(key, lam, tuple(shape))
    return out.astype(np_dtype(dtype))


@register("_random_negative_binomial", aliases=("random_negative_binomial",))
def random_negative_binomial(key, k=1, p=1.0, shape=(1,), dtype="float32", **_):
    """Negative-binomial counts (failures `k`, success prob `p`) via the
    gamma-Poisson mixture (reference: _random_negative_binomial)."""
    _check_param("random_negative_binomial", "k", k, lambda v: v > 0)
    _check_param("random_negative_binomial", "p", p, lambda v: 0 < v <= 1)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, float(k), tuple(shape)) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(np_dtype(dtype))


@register("_random_generalized_negative_binomial",
          aliases=("random_generalized_negative_binomial",))
def random_gen_neg_binomial(key, mu=1.0, alpha=1.0, shape=(1,), dtype="float32", **_):
    """Generalized negative-binomial counts parameterized by mean `mu`
    and dispersion `alpha` (reference:
    _random_generalized_negative_binomial, sample_op.cc)."""
    _check_param("random_generalized_negative_binomial", "mu", mu,
                 lambda v: v > 0)
    _check_param("random_generalized_negative_binomial", "alpha", alpha,
                 lambda v: v > 0)
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, tuple(shape)) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(np_dtype(dtype))


@register("_random_randint", aliases=("random_randint", "randint"))
def random_randint(key, low=0, high=1, shape=(1,), dtype="int32", **_):
    """Uniform integers in ``[low, high)`` of `shape`
    (reference: _random_randint, sample_op.cc)."""
    return jax.random.randint(key, tuple(shape), int(low), int(high),
                              dtype=np_dtype(dtype))


@register("_sample_multinomial", aliases=("sample_multinomial",),
          num_outputs=lambda attrs: 2 if attrs.get("get_prob") else 1)
def sample_multinomial(key, data, shape=(), get_prob=False, dtype="int32",
                       **_):
    """Draw `shape` categorical indices per row of probabilities `data`;
    with ``get_prob`` also return the per-draw log-likelihood (second
    output, used for REINFORCE) (reference: _sample_multinomial)."""
    n = int(shape[0]) if shape else 1
    logits = jnp.log(jnp.maximum(data, 1e-37))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
    else:
        out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                     shape=(data.shape[0], n))
    if get_prob:
        # log-likelihood of each drawn class (reference: second output
        # of sample_multinomial when get_prob=True, used for REINFORCE).
        # Gather before the no-shape squeeze so 2-D data with the
        # default shape=() takes the same take_along_axis path.
        logp = logits - jax.scipy.special.logsumexp(logits, axis=-1,
                                                    keepdims=True)
        if data.ndim == 1:
            ll = logp[out.astype(jnp.int32)]
        else:
            ll = jnp.take_along_axis(logp, out.astype(jnp.int32), axis=-1)
    if not shape:
        out = out.squeeze(-1) if out.ndim > 1 else out[0]
        if get_prob:
            ll = ll.squeeze(-1) if ll.ndim > 1 else ll[0]
    out = out.astype(np_dtype(dtype))
    if not get_prob:
        return out
    return out, ll.astype(jnp.float32)


@register("_sample_uniform", aliases=("sample_uniform",))
def sample_uniform(key, low, high, shape=(), dtype="float32", **_):
    """Per-element uniform draws: one `shape`-tailed sample for every
    (low, high) pair (reference: _sample_uniform, sample_op.cc)."""
    d = np_dtype(dtype)
    tail = tuple(shape) if shape else ()
    u = jax.random.uniform(key, low.shape + tail, dtype=d)
    low = low.reshape(low.shape + (1,) * len(tail))
    high = high.reshape(high.shape + (1,) * len(tail))
    return low + u * (high - low)


@register("_sample_normal", aliases=("sample_normal",))
def sample_normal(key, mu, sigma, shape=(), dtype="float32", **_):
    """Per-element Gaussian draws for every (mu, sigma) pair
    (reference: _sample_normal, sample_op.cc)."""
    d = np_dtype(dtype)
    tail = tuple(shape) if shape else ()
    z = jax.random.normal(key, mu.shape + tail, dtype=d)
    mu = mu.reshape(mu.shape + (1,) * len(tail))
    sigma = sigma.reshape(sigma.shape + (1,) * len(tail))
    return mu + z * sigma


@register("_sample_gamma", aliases=("sample_gamma",))
def sample_gamma(key, alpha, beta, shape=(), dtype="float32", **_):
    """Per-element gamma draws for every (alpha, beta) pair
    (reference: _sample_gamma, sample_op.cc)."""
    d = np_dtype(dtype)
    tail = tuple(shape) if shape else ()
    alpha_b = alpha.reshape(alpha.shape + (1,) * len(tail))
    g = jax.random.gamma(key, jnp.broadcast_to(alpha_b, alpha.shape + tail), dtype=d)
    beta = beta.reshape(beta.shape + (1,) * len(tail))
    return g * beta


@register("_sample_exponential", aliases=("sample_exponential",))
def sample_exponential(key, lam, shape=(), dtype="float32", **_):
    """Per-element exponential draws for every rate in `lam`
    (reference: _sample_exponential, sample_op.cc)."""
    d = np_dtype(dtype)
    tail = tuple(shape) if shape else ()
    e = jax.random.exponential(key, lam.shape + tail, dtype=d)
    return e / lam.reshape(lam.shape + (1,) * len(tail))


def _bcast_tail(arr, tail):
    return jnp.broadcast_to(arr.reshape(arr.shape + (1,) * len(tail)),
                            arr.shape + tail)


@register("_sample_poisson", aliases=("sample_poisson",))
def sample_poisson(key, lam, shape=(), dtype="float32", **_):
    """Per-element Poisson counts for every mean in `lam`
    (reference: _sample_poisson, sample_op.cc)."""
    tail = tuple(shape) if shape else ()
    return jax.random.poisson(key, _bcast_tail(lam, tail)).astype(
        np_dtype(dtype))


@register("_sample_negative_binomial", aliases=("sample_negative_binomial",))
def sample_negative_binomial(key, k, p, shape=(), dtype="float32", **_):
    """Per-element negative-binomial counts for every (k, p) pair via
    the gamma-Poisson mixture (reference: _sample_negative_binomial)."""
    k1, k2 = jax.random.split(key)
    tail = tuple(shape) if shape else ()
    k_b = _bcast_tail(k.astype(jnp.float32), tail)
    p_b = _bcast_tail(p, tail)
    lam = jax.random.gamma(k1, k_b) * ((1.0 - p_b) / p_b)
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


@register("_sample_generalized_negative_binomial",
          aliases=("sample_generalized_negative_binomial",))
def sample_gen_negative_binomial(key, mu, alpha, shape=(), dtype="float32",
                                 **_):
    """Per-element generalized negative-binomial counts for every
    (mu, alpha) pair (reference:
    _sample_generalized_negative_binomial, sample_op.cc)."""
    k1, k2 = jax.random.split(key)
    tail = tuple(shape) if shape else ()
    r = 1.0 / _bcast_tail(alpha, tail)
    p = r / (r + _bcast_tail(mu, tail))
    lam = jax.random.gamma(k1, r) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


@register("_shuffle", aliases=("shuffle",))
def shuffle(key, data, **_):
    """Random permutation of `data` along axis 0
    (reference: _shuffle, shuffle_op.cc)."""
    return jax.random.permutation(key, data, axis=0)


@register("_sample_unique_zipfian")
def sample_unique_zipfian(key, range_max=1, shape=(1,), **_):
    """Approximately Zipfian (log-uniform) candidate indices in
    ``[0, range_max)`` — sampled-softmax candidates (reference:
    _sample_unique_zipfian, unique_sample_op.cc; approximate: samples
    are not deduplicated)."""
    # approximate: log-uniform samples (used by sampled softmax candidates)
    n = int(shape[-1]) if shape else 1
    u = jax.random.uniform(key, (n,))
    out = jnp.exp(u * jnp.log(float(range_max))).astype(jnp.int64) - 1
    return jnp.clip(out, 0, int(range_max) - 1)
