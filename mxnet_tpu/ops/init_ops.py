"""Creation ops: zeros/ones/full/arange/eye and random samplers.

Reference: src/operator/tensor/init_op.cc, src/operator/random/
(sample_op.cc multinomial_op.cc unique_sample_op.cc) and
include/mxnet/random_generator.h.

Random ops take an explicit PRNG ``key`` argument (pure functions); the
NDArray layer threads keys from the global/trace-scoped generator in
mxnet_tpu/random.py — the TPU-native replacement for the reference's
per-device RNG resource (src/resource.cc kRandom).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import np_dtype
from .registry import register


@register("_zeros", aliases=("zeros",))
def zeros(shape=(), dtype="float32", **_):
    return jnp.zeros(tuple(shape), dtype=np_dtype(dtype))


@register("_ones", aliases=("ones",))
def ones(shape=(), dtype="float32", **_):
    return jnp.ones(tuple(shape), dtype=np_dtype(dtype))


@register("_full", aliases=("full",))
def full(shape=(), value=0.0, dtype="float32", **_):
    return jnp.full(tuple(shape), value, dtype=np_dtype(dtype))


@register("zeros_like")
def zeros_like(x, **_):
    return jnp.zeros_like(x)


@register("ones_like")
def ones_like(x, **_):
    return jnp.ones_like(x)


@register("_arange", aliases=("arange",))
def arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32", **_):
    out = jnp.arange(start, stop, step, dtype=np_dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, int(repeat))
    return out


@register("_linspace", aliases=("linspace",))
def linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32", **_):
    return jnp.linspace(start, stop, int(num), endpoint=bool(endpoint),
                        dtype=np_dtype(dtype))


@register("_eye", aliases=("eye",))
def eye(N=1, M=0, k=0, dtype="float32", **_):
    m = int(M) if M else int(N)
    return jnp.eye(int(N), m, k=int(k), dtype=np_dtype(dtype))


# ------------------------------------------------------------------- random

# All samplers: fn(key, [dist-param tensors...], shape=..., dtype=...)


def _check_param(op, name, value, ok):
    """Reject invalid SCALAR distribution parameters at dispatch, like
    the reference kernels' CHECK macros (src/operator/random/
    sample_op.h; surfaced there as a deferred engine error, here
    synchronously).  Array-valued params are validated nowhere cheap —
    same as feeding NaNs: garbage in, garbage out."""
    if isinstance(value, (int, float)) and not ok(value):
        from ..base import MXNetError

        raise MXNetError("%s: invalid %s=%r" % (op, name, value))


@register("_random_uniform", aliases=("random_uniform", "uniform"))
def random_uniform(key, low=0.0, high=1.0, shape=(1,), dtype="float32", **_):
    d = np_dtype(dtype)
    return jax.random.uniform(key, tuple(shape), dtype=d, minval=low, maxval=high)


@register("_random_normal", aliases=("random_normal", "normal"))
def random_normal(key, loc=0.0, scale=1.0, shape=(1,), dtype="float32", **_):
    _check_param("random_normal", "scale", scale, lambda v: v >= 0)
    d = np_dtype(dtype)
    return jax.random.normal(key, tuple(shape), dtype=d) * scale + loc


@register("_random_gamma", aliases=("random_gamma",))
def random_gamma(key, alpha=1.0, beta=1.0, shape=(1,), dtype="float32", **_):
    _check_param("random_gamma", "alpha", alpha, lambda v: v > 0)
    _check_param("random_gamma", "beta", beta, lambda v: v > 0)
    d = np_dtype(dtype)
    return jax.random.gamma(key, alpha, tuple(shape), dtype=d) * beta


@register("_random_exponential", aliases=("random_exponential",))
def random_exponential(key, lam=1.0, shape=(1,), dtype="float32", **_):
    _check_param("random_exponential", "lam", lam, lambda v: v > 0)
    d = np_dtype(dtype)
    return jax.random.exponential(key, tuple(shape), dtype=d) / lam


@register("_random_poisson", aliases=("random_poisson",))
def random_poisson(key, lam=1.0, shape=(1,), dtype="float32", **_):
    _check_param("random_poisson", "lam", lam, lambda v: v >= 0)
    out = jax.random.poisson(key, lam, tuple(shape))
    return out.astype(np_dtype(dtype))


@register("_random_negative_binomial", aliases=("random_negative_binomial",))
def random_negative_binomial(key, k=1, p=1.0, shape=(1,), dtype="float32", **_):
    _check_param("random_negative_binomial", "k", k, lambda v: v > 0)
    _check_param("random_negative_binomial", "p", p, lambda v: 0 < v <= 1)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, float(k), tuple(shape)) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(np_dtype(dtype))


@register("_random_generalized_negative_binomial",
          aliases=("random_generalized_negative_binomial",))
def random_gen_neg_binomial(key, mu=1.0, alpha=1.0, shape=(1,), dtype="float32", **_):
    _check_param("random_generalized_negative_binomial", "mu", mu,
                 lambda v: v > 0)
    _check_param("random_generalized_negative_binomial", "alpha", alpha,
                 lambda v: v > 0)
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, tuple(shape)) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(np_dtype(dtype))


@register("_random_randint", aliases=("random_randint", "randint"))
def random_randint(key, low=0, high=1, shape=(1,), dtype="int32", **_):
    return jax.random.randint(key, tuple(shape), int(low), int(high),
                              dtype=np_dtype(dtype))


@register("_sample_multinomial", aliases=("sample_multinomial",),
          num_outputs=lambda attrs: 2 if attrs.get("get_prob") else 1)
def sample_multinomial(key, data, shape=(), get_prob=False, dtype="int32",
                       **_):
    n = int(shape[0]) if shape else 1
    logits = jnp.log(jnp.maximum(data, 1e-37))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
    else:
        out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                     shape=(data.shape[0], n))
    if get_prob:
        # log-likelihood of each drawn class (reference: second output
        # of sample_multinomial when get_prob=True, used for REINFORCE).
        # Gather before the no-shape squeeze so 2-D data with the
        # default shape=() takes the same take_along_axis path.
        logp = logits - jax.scipy.special.logsumexp(logits, axis=-1,
                                                    keepdims=True)
        if data.ndim == 1:
            ll = logp[out.astype(jnp.int32)]
        else:
            ll = jnp.take_along_axis(logp, out.astype(jnp.int32), axis=-1)
    if not shape:
        out = out.squeeze(-1) if out.ndim > 1 else out[0]
        if get_prob:
            ll = ll.squeeze(-1) if ll.ndim > 1 else ll[0]
    out = out.astype(np_dtype(dtype))
    if not get_prob:
        return out
    return out, ll.astype(jnp.float32)


@register("_sample_uniform", aliases=("sample_uniform",))
def sample_uniform(key, low, high, shape=(), dtype="float32", **_):
    d = np_dtype(dtype)
    tail = tuple(shape) if shape else ()
    u = jax.random.uniform(key, low.shape + tail, dtype=d)
    low = low.reshape(low.shape + (1,) * len(tail))
    high = high.reshape(high.shape + (1,) * len(tail))
    return low + u * (high - low)


@register("_sample_normal", aliases=("sample_normal",))
def sample_normal(key, mu, sigma, shape=(), dtype="float32", **_):
    d = np_dtype(dtype)
    tail = tuple(shape) if shape else ()
    z = jax.random.normal(key, mu.shape + tail, dtype=d)
    mu = mu.reshape(mu.shape + (1,) * len(tail))
    sigma = sigma.reshape(sigma.shape + (1,) * len(tail))
    return mu + z * sigma


@register("_sample_gamma", aliases=("sample_gamma",))
def sample_gamma(key, alpha, beta, shape=(), dtype="float32", **_):
    d = np_dtype(dtype)
    tail = tuple(shape) if shape else ()
    alpha_b = alpha.reshape(alpha.shape + (1,) * len(tail))
    g = jax.random.gamma(key, jnp.broadcast_to(alpha_b, alpha.shape + tail), dtype=d)
    beta = beta.reshape(beta.shape + (1,) * len(tail))
    return g * beta


@register("_sample_exponential", aliases=("sample_exponential",))
def sample_exponential(key, lam, shape=(), dtype="float32", **_):
    d = np_dtype(dtype)
    tail = tuple(shape) if shape else ()
    e = jax.random.exponential(key, lam.shape + tail, dtype=d)
    return e / lam.reshape(lam.shape + (1,) * len(tail))


def _bcast_tail(arr, tail):
    return jnp.broadcast_to(arr.reshape(arr.shape + (1,) * len(tail)),
                            arr.shape + tail)


@register("_sample_poisson", aliases=("sample_poisson",))
def sample_poisson(key, lam, shape=(), dtype="float32", **_):
    tail = tuple(shape) if shape else ()
    return jax.random.poisson(key, _bcast_tail(lam, tail)).astype(
        np_dtype(dtype))


@register("_sample_negative_binomial", aliases=("sample_negative_binomial",))
def sample_negative_binomial(key, k, p, shape=(), dtype="float32", **_):
    k1, k2 = jax.random.split(key)
    tail = tuple(shape) if shape else ()
    k_b = _bcast_tail(k.astype(jnp.float32), tail)
    p_b = _bcast_tail(p, tail)
    lam = jax.random.gamma(k1, k_b) * ((1.0 - p_b) / p_b)
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


@register("_sample_generalized_negative_binomial",
          aliases=("sample_generalized_negative_binomial",))
def sample_gen_negative_binomial(key, mu, alpha, shape=(), dtype="float32",
                                 **_):
    k1, k2 = jax.random.split(key)
    tail = tuple(shape) if shape else ()
    r = 1.0 / _bcast_tail(alpha, tail)
    p = r / (r + _bcast_tail(mu, tail))
    lam = jax.random.gamma(k1, r) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


@register("_shuffle", aliases=("shuffle",))
def shuffle(key, data, **_):
    return jax.random.permutation(key, data, axis=0)


@register("_sample_unique_zipfian")
def sample_unique_zipfian(key, range_max=1, shape=(1,), **_):
    # approximate: log-uniform samples (used by sampled softmax candidates)
    n = int(shape[-1]) if shape else 1
    u = jax.random.uniform(key, (n,))
    out = jnp.exp(u * jnp.log(float(range_max))).astype(jnp.int64) - 1
    return jnp.clip(out, 0, int(range_max) - 1)
