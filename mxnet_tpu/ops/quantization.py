"""INT8 quantization operators.

Reference: src/operator/quantization/ — quantize{,-v2,-inl.h},
dequantize, requantize, quantized_{conv,fully_connected,pooling,flatten,
concat} and quantization_utils.h (zero-centered int8 / affine uint8
mappings, QuantizationRangeForMultiplication).

TPU-native design: int8 matmul/conv feed the MXU directly —
``lax.dot_general``/``lax.conv_general_dilated`` on int8 operands with
``preferred_element_type=int32`` accumulate in int32 exactly like the
reference's DP4A/MKLDNN kernels.  Ranges ride as scalar float arrays
(shape (1,)) alongside the quantized tensor, same 3-output convention
(out, min_range, max_range) as the reference so the graph pass and the
Python calibration API line up 1:1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import OP_INPUT_NAMES, register

INT8_MAX = 127.0
INT32_MAX = 2147483647.0


def _zero_centered_quantize(x, real_range):
    """float -> int8, symmetric (reference quantize_zero_centered)."""
    real_range = jnp.maximum(real_range, 1e-30)
    scale = INT8_MAX / real_range
    q = jnp.clip(jnp.rint(x * scale), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8)


def _affine_quantize_u8(x, mn, mx):
    """float -> uint8 affine (reference quantize_unsigned)."""
    rng = jnp.maximum(mx - mn, 1e-30)
    scale = 255.0 / rng
    q = jnp.clip(jnp.rint((x - mn) * scale), 0.0, 255.0)
    return q.astype(jnp.uint8)


def _s1(v):
    return jnp.reshape(jnp.asarray(v, jnp.float32), (1,))


@register("_contrib_quantize", aliases=("quantize",), num_outputs=3)
def quantize(data, min_range, max_range, out_type="uint8", **_):
    """(data, min, max) -> (q, out_min, out_max).

    int8: zero-centered symmetric over max(|min|,|max|); uint8: affine.
    Reference: quantize-inl.h QuantizeCompute."""
    mn = jnp.reshape(min_range, ())
    mx = jnp.reshape(max_range, ())
    if out_type == "int8":
        real = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        return (_zero_centered_quantize(data, real), _s1(-real), _s1(real))
    return (_affine_quantize_u8(data, mn, mx), _s1(mn), _s1(mx))


@register("_contrib_quantize_v2", aliases=("quantize_v2",), num_outputs=3)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8", **_):
    """Like quantize but derives the range from the data when no calib
    range is given (reference: quantize_v2-inl.h)."""
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.asarray(float(min_calib_range), jnp.float32)
        mx = jnp.asarray(float(max_calib_range), jnp.float32)
    else:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    if out_type == "int8":
        real = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        return (_zero_centered_quantize(data, real), _s1(-real), _s1(real))
    return (_affine_quantize_u8(data, mn, mx), _s1(mn), _s1(mx))


@register("_contrib_dequantize", aliases=("dequantize",), num_outputs=1)
def dequantize(data, min_range, max_range, out_type="float32", **_):
    """int8/uint8/int32 -> float32 (reference: dequantize-inl.h)."""
    mn = jnp.reshape(min_range, ())
    mx = jnp.reshape(max_range, ())
    if data.dtype == jnp.uint8:
        scale = (mx - mn) / 255.0
        return data.astype(jnp.float32) * scale + mn
    # zero-centered signed types: value = q * real_range / q_max
    qmax = INT8_MAX if data.dtype == jnp.int8 else INT32_MAX
    real = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    return data.astype(jnp.float32) * (real / qmax)


@register("_contrib_requantize", aliases=("requantize",), num_outputs=3)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None, **_):
    """int32 (+its float range) -> int8.  With calib ranges, clips to the
    calibrated real range (reference: requantize-inl.h RequantizeForward);
    otherwise uses the actual min/max of the int32 data."""
    mn = jnp.reshape(min_range, ())
    mx = jnp.reshape(max_range, ())
    in_real = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    as_float = data.astype(jnp.float32) * (in_real / INT32_MAX)
    if min_calib_range is not None and max_calib_range is not None:
        real = jnp.maximum(abs(float(min_calib_range)),
                           abs(float(max_calib_range)))
        real = jnp.asarray(real, jnp.float32)
    else:
        amax = jnp.max(jnp.abs(data)).astype(jnp.float32)
        real = amax * (in_real / INT32_MAX)
    return (_zero_centered_quantize(as_float, real), _s1(-real), _s1(real))


def _mul_range(max_d, max_w):
    """Float range represented by an int32 accumulator produced from two
    zero-centered int8 operands (reference: quantization_utils.h
    QuantizationRangeForMultiplication): one int32 unit = (range_d/127) *
    (range_w/127); the representable range is ±INT32_MAX units."""
    unit = (max_d / INT8_MAX) * (max_w / INT8_MAX)
    real = unit * INT32_MAX
    return -real, real


@register("_contrib_quantized_fully_connected", num_outputs=3)
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias, max_bias,
                              num_hidden=None, no_bias=False, flatten=True,
                              **_):
    """int8 data × int8 weight -> int32 (reference:
    quantized_fully_connected.cc).  Bias (int8) is rescaled into the
    accumulator's scale before adding, as the reference does."""
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    out = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    max_d = jnp.maximum(jnp.abs(jnp.reshape(min_data, ())),
                        jnp.abs(jnp.reshape(max_data, ())))
    max_w = jnp.maximum(jnp.abs(jnp.reshape(min_weight, ())),
                        jnp.abs(jnp.reshape(max_weight, ())))
    mn, mx = _mul_range(max_d, max_w)
    if not no_bias and bias is not None:
        # bias int8 in its own scale -> accumulator units
        max_b = jnp.maximum(jnp.abs(jnp.reshape(min_bias, ())),
                            jnp.abs(jnp.reshape(max_bias, ())))
        acc_unit = jnp.maximum(mx / INT32_MAX, 1e-30)
        bias_f = bias.astype(jnp.float32) * (max_b / INT8_MAX)
        out = out + jnp.rint(bias_f / acc_unit).astype(jnp.int32)
    return out, _s1(mn), _s1(mx)


@register("_contrib_quantized_conv", num_outputs=3)
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias, max_bias, kernel=None, stride=None,
                   pad=None, dilate=None, num_filter=None, no_bias=False,
                   layout="NCHW", **_):
    """int8 NCHW conv -> int32 accumulator (reference: quantized_conv.cc).
    XLA lowers integer conv onto the MXU with int32 accumulation."""
    ndim = data.ndim - 2
    stride = tuple(int(s) for s in (stride or (1,) * ndim))
    pad = tuple(int(p) for p in (pad or (0,) * ndim))
    dilate = tuple(int(d) for d in (dilate or (1,) * ndim))
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if ndim == 2 else ("NCW", "OIW", "NCW"))
    out = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8), stride,
        [(p, p) for p in pad], rhs_dilation=dilate, dimension_numbers=dn,
        preferred_element_type=jnp.int32)
    max_d = jnp.maximum(jnp.abs(jnp.reshape(min_data, ())),
                        jnp.abs(jnp.reshape(max_data, ())))
    max_w = jnp.maximum(jnp.abs(jnp.reshape(min_weight, ())),
                        jnp.abs(jnp.reshape(max_weight, ())))
    mn, mx = _mul_range(max_d, max_w)
    if not no_bias and bias is not None:
        max_b = jnp.maximum(jnp.abs(jnp.reshape(min_bias, ())),
                            jnp.abs(jnp.reshape(max_bias, ())))
        acc_unit = jnp.maximum(mx / INT32_MAX, 1e-30)
        bias_f = bias.astype(jnp.float32) * (max_b / INT8_MAX)
        bias_i = jnp.rint(bias_f / acc_unit).astype(jnp.int32)
        out = out + bias_i.reshape((1, -1) + (1,) * ndim)
    return out, _s1(mn), _s1(mx)


@register("_contrib_quantized_pooling", num_outputs=3)
def quantized_pooling(data, min_data, max_data, kernel=None, stride=None,
                      pad=None, pool_type="max", global_pool=False, **_):
    """Pooling on quantized data; range passes through unchanged
    (reference: quantized_pooling.cc)."""
    from .nn import pooling  # same lowering as the float op

    out = pooling(data.astype(jnp.float32), kernel=kernel or (),
                  stride=stride or (), pad=pad or (), pool_type=pool_type,
                  global_pool=global_pool)
    if pool_type == "max":
        out = out.astype(data.dtype)
    elif data.dtype == jnp.uint8:  # avg pooling rounds back in-range
        out = jnp.clip(jnp.rint(out), 0, 255).astype(jnp.uint8)
    else:
        out = jnp.clip(jnp.rint(out), -128, 127).astype(jnp.int8)
    return out, _s1(jnp.reshape(min_data, ())), _s1(jnp.reshape(max_data, ()))


@register("_contrib_quantized_flatten", num_outputs=3)
def quantized_flatten(data, min_data, max_data, **_):
    """Flatten quantized data to (batch, -1), passing the calibration
    range through unchanged — layout-only, so the int8 values and
    their scale are untouched (reference: quantization/
    quantized_flatten.cc)."""
    return (data.reshape(data.shape[0], -1),
            _s1(jnp.reshape(min_data, ())), _s1(jnp.reshape(max_data, ())))


@register("_contrib_quantized_concat", num_outputs=3)
def quantized_concat(*args, dim=1, num_args=None, **_):
    """Concat int8 inputs: requantize all to the widest range first
    (reference: quantized_concat.cc)."""
    n = len(args) // 3
    datas, mins, maxs = args[:n], args[n:2 * n], args[2 * n:]
    reals = [jnp.maximum(jnp.abs(jnp.reshape(mn, ())),
                         jnp.abs(jnp.reshape(mx, ())))
             for mn, mx in zip(mins, maxs)]
    real_out = jnp.stack(reals).max()
    scaled = [jnp.clip(jnp.rint(d.astype(jnp.float32) * (r / real_out)),
                       -INT8_MAX, INT8_MAX).astype(jnp.int8)
              for d, r in zip(datas, reals)]
    return (jnp.concatenate(scaled, axis=int(dim)),
            _s1(-real_out), _s1(real_out))


OP_INPUT_NAMES.update({
    "_contrib_quantize": ("data", "min_range", "max_range"),
    "_contrib_quantize_v2": ("data",),
    "_contrib_dequantize": ("data", "min_range", "max_range"),
    "_contrib_requantize": ("data", "min_range", "max_range"),
    "_contrib_quantized_fully_connected": (
        "data", "weight", "bias", "min_data", "max_data", "min_weight",
        "max_weight", "min_bias", "max_bias"),
    "_contrib_quantized_conv": (
        "data", "weight", "bias", "min_data", "max_data", "min_weight",
        "max_weight", "min_bias", "max_bias"),
    "_contrib_quantized_pooling": ("data", "min_data", "max_data"),
    "_contrib_quantized_flatten": ("data", "min_data", "max_data"),
})
