"""Contrib operators: detection (SSD/RCNN), resize, pooling, masking.

Reference: src/operator/contrib/ — multibox_{prior,target,detection}.cc
(SSD anchors/matching/decode), bounding_box.cc (box_nms, box_iou),
roi_align.cc, bilinear_resize.cc, adaptive_avg_pooling.cc,
boolean_mask.cc, index_copy.cc, quadratic_op.cc.

TPU-native notes: the reference kernels use data-dependent shapes and
per-row dynamic loops; here every op is a fixed-capacity masked
computation so XLA gets static shapes (SURVEY.md §7 'SSD custom ops'):
NMS keeps all boxes, marking suppressed entries -1; boolean_mask
returns a fixed-size prefix buffer padded with zeros.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ----------------------------------------------------------------- helpers


def _corner_iou(a, b):
    """IoU of boxes in corner format. a: (..., M, 4), b: (..., N, 4) →
    (..., M, N)."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)       # (..., M, 1)
    bx1, by1, bx2, by2 = [v.squeeze(-1) for v in jnp.split(b, 4, axis=-1)]
    ix1 = jnp.maximum(ax1, bx1[..., None, :])
    iy1 = jnp.maximum(ay1, by1[..., None, :])
    ix2 = jnp.minimum(ax2, bx2[..., None, :])
    iy2 = jnp.minimum(ay2, by2[..., None, :])
    iw = jnp.clip(ix2 - ix1, 0, None)
    ih = jnp.clip(iy2 - iy1, 0, None)
    inter = iw * ih
    area_a = jnp.clip(ax2 - ax1, 0, None) * jnp.clip(ay2 - ay1, 0, None)
    area_b = jnp.clip(bx2 - bx1, 0, None) * jnp.clip(by2 - by1, 0, None)
    union = area_a + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


# ----------------------------------------------------------------- boxes


@register("box_iou", aliases=("_contrib_box_iou",))
def box_iou(lhs, rhs, format="corner", **_):
    """reference: src/operator/contrib/bounding_box.cc BoxIoU."""
    if format == "center":
        lhs = _center_to_corner(lhs)
        rhs = _center_to_corner(rhs)
    return _corner_iou(lhs, rhs)


def _center_to_corner(b):
    x, y, w, h = jnp.split(b, 4, axis=-1)
    return jnp.concatenate(
        [x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


@register("box_nms", aliases=("_contrib_box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner", **_):
    """Fixed-capacity NMS (reference: bounding_box.cc BoxNMS).

    data: (..., N, K) rows [id?, score, x1,y1,x2,y2, ...]; suppressed
    rows get score -1 (reference semantics), order sorted by score.
    """
    cs, si, ii = int(coord_start), int(score_index), int(id_index)
    batch_shape = data.shape[:-2]
    n, k = data.shape[-2], data.shape[-1]
    flat = data.reshape((-1, n, k))

    def one(rows):
        scores = rows[:, si]
        order = jnp.argsort(-scores)
        rows_s = rows[order]
        scores_s = rows_s[:, si]
        boxes = rows_s[:, cs:cs + 4]
        if in_format == "center":
            boxes = _center_to_corner(boxes)
        ious = _corner_iou(boxes, boxes)
        valid = scores_s > valid_thresh
        if topk > 0:
            valid = valid & (jnp.arange(n) < topk)
        if ii >= 0 and not force_suppress:
            ids = rows_s[:, ii]
            same_class = ids[:, None] == ids[None, :]
        else:
            same_class = jnp.ones((n, n), dtype=bool)

        def body(i, keep):
            sup = keep[i] & valid[i]
            over = (ious[i] > overlap_thresh) & same_class[i] & \
                (jnp.arange(n) > i)
            return jnp.where(sup & over, False, keep)

        keep = lax.fori_loop(0, n, body, jnp.ones((n,), dtype=bool))
        keep = keep & valid
        new_scores = jnp.where(keep, scores_s, -1.0)
        out = rows_s.at[:, si].set(new_scores)
        return out

    out = jax.vmap(one)(flat)
    return out.reshape(batch_shape + (n, k))


# ----------------------------------------------------------------- multibox


@register("MultiBoxPrior", aliases=("multibox_prior", "_contrib_MultiBoxPrior"))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0),
                   offsets=(0.5, 0.5), **_):
    """SSD anchor generation (reference: contrib/multibox_prior.cc).

    data: (B, C, H, W) → anchors (1, H*W*(S+R-1), 4) corner format.
    """
    h, w = data.shape[2], data.shape[3]
    sizes = tuple(float(s) for s in (sizes if hasattr(sizes, "__len__") else (sizes,)))
    ratios = tuple(float(r) for r in (ratios if hasattr(ratios, "__len__") else (ratios,)))
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")     # (H, W)
    centers = jnp.stack([cxg, cyg], axis=-1).reshape(-1, 2)  # (HW, 2) x,y

    wh = []
    # reference order: (s1,r1), (s2,r1), ..., (s1,r2), (s1,r3)...
    for s in sizes:
        wh.append((s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0])))
    for r in ratios[1:]:
        wh.append((sizes[0] * jnp.sqrt(r), sizes[0] / jnp.sqrt(r)))
    wh = jnp.asarray(wh)                                # (A, 2)
    a = wh.shape[0]
    cxy = jnp.repeat(centers, a, axis=0)                # (HW*A, 2)
    whs = jnp.tile(wh, (centers.shape[0], 1))           # (HW*A, 2)
    anchors = jnp.concatenate([cxy - whs / 2, cxy + whs / 2], axis=-1)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors[None].astype(data.dtype)


@register("MultiBoxTarget", aliases=("multibox_target", "_contrib_MultiBoxTarget"),
          num_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2), **_):
    """SSD target matching (reference: contrib/multibox_target.cc).

    anchor: (1, N, 4) corners; label: (B, M, 5) [cls, x1,y1,x2,y2] with
    -1 padding; cls_pred: (B, C+1, N) (unused beyond shape, kept for
    negative mining parity).  Returns (loc_target (B, N*4),
    loc_mask (B, N*4), cls_target (B, N)).
    """
    anchors = anchor[0]                                  # (N, 4)
    n = anchors.shape[0]

    def one(lbl, cpred):
        gt_valid = lbl[:, 0] >= 0                        # (M,)
        gt_boxes = lbl[:, 1:5]
        ious = _corner_iou(anchors, gt_boxes)            # (N, M)
        ious = jnp.where(gt_valid[None, :], ious, -1.0)
        best_gt = jnp.argmax(ious, axis=1)               # (N,)
        best_iou = jnp.max(ious, axis=1)
        # bipartite stage: each valid gt claims its best anchor; an
        # explicit (M, N) claim matrix avoids scatter collisions between
        # valid and padded gt rows
        best_anchor = jnp.argmax(ious, axis=0)           # (M,)
        m = lbl.shape[0]
        claim = (best_anchor[:, None] ==
                 jnp.arange(n)[None, :]) & gt_valid[:, None]  # (M, N)
        claimed = claim.any(axis=0)
        claimed_gt = jnp.argmax(claim, axis=0).astype(jnp.int32)
        pos = claimed | (best_iou >= overlap_threshold)
        match = jnp.where(claimed, claimed_gt, best_gt)

        matched_box = gt_boxes[match]                    # (N, 4)
        # encode regression target in center format / variances
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        aw = jnp.clip(anchors[:, 2] - anchors[:, 0], 1e-8, None)
        ah = jnp.clip(anchors[:, 3] - anchors[:, 1], 1e-8, None)
        gcx = (matched_box[:, 0] + matched_box[:, 2]) / 2
        gcy = (matched_box[:, 1] + matched_box[:, 3]) / 2
        gw = jnp.clip(matched_box[:, 2] - matched_box[:, 0], 1e-8, None)
        gh = jnp.clip(matched_box[:, 3] - matched_box[:, 1], 1e-8, None)
        tx = (gcx - acx) / aw / variances[0]
        ty = (gcy - acy) / ah / variances[1]
        tw = jnp.log(gw / aw) / variances[2]
        th = jnp.log(gh / ah) / variances[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)     # (N, 4)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.repeat(pos.astype(jnp.float32), 4)
        if negative_mining_ratio > 0:
            # hard-negative mining (reference multibox_target.cc:181-239):
            # candidates = non-positive anchors with best_iou below the
            # mining threshold; ranked by ascending background softmax
            # probability (hardest negatives first); top num_pos*ratio
            # (but at least minimum_negative_samples) become background,
            # everything else unmatched is ignore_label
            bg_prob = jax.nn.softmax(cpred, axis=0)[0]   # (N,)
            cand = (~pos) & (best_iou < negative_mining_thresh)
            num_pos = jnp.sum(pos)
            num_neg = jnp.maximum(
                (num_pos * negative_mining_ratio).astype(jnp.int32),
                int(minimum_negative_samples))
            num_neg = jnp.minimum(num_neg, n - num_pos)
            key = jnp.where(cand, bg_prob, jnp.inf)      # ascending sort
            order = jnp.argsort(key)
            rank = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n))
            neg = cand & (rank < num_neg)
            cls_t = jnp.where(pos, lbl[match, 0] + 1.0,
                              jnp.where(neg, 0.0, float(ignore_label)))
        else:
            cls_t = jnp.where(pos, lbl[match, 0] + 1.0, 0.0)  # bg = 0
        return loc_t, loc_m, cls_t

    loc_target, loc_mask, cls_target = jax.vmap(one)(label, cls_pred)
    return (loc_target.astype(anchor.dtype), loc_mask.astype(anchor.dtype),
            cls_target.astype(anchor.dtype))


@register("MultiBoxDetection",
          aliases=("multibox_detection", "_contrib_MultiBoxDetection"))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1, **_):
    """SSD decode + NMS (reference: contrib/multibox_detection.cc).

    cls_prob: (B, C+1, N), loc_pred: (B, N*4), anchor: (1, N, 4) →
    (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], suppressed = -1.
    """
    b = cls_prob.shape[0]
    n = anchor.shape[1]
    anchors = anchor[0]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]

    loc = loc_pred.reshape((b, n, 4))
    cx = loc[..., 0] * variances[0] * aw + acx
    cy = loc[..., 1] * variances[1] * ah + acy
    w = jnp.exp(loc[..., 2] * variances[2]) * aw
    h = jnp.exp(loc[..., 3] * variances[3]) * ah
    boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                      axis=-1)                           # (B, N, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)

    # best foreground class per anchor
    fg = jnp.concatenate([cls_prob[:, :background_id],
                          cls_prob[:, background_id + 1:]], axis=1)
    cls_id = jnp.argmax(fg, axis=1).astype(cls_prob.dtype)  # (B, N)
    score = jnp.max(fg, axis=1)
    keep = score > threshold
    cls_id = jnp.where(keep, cls_id, -1.0)
    score = jnp.where(keep, score, -1.0)
    rows = jnp.concatenate([cls_id[..., None], score[..., None], boxes],
                           axis=-1)                      # (B, N, 6)
    out = box_nms(rows, overlap_thresh=nms_threshold, valid_thresh=0.0,
                  topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                  force_suppress=force_suppress)
    # reference marks suppressed rows' class id -1
    sup = out[..., 1] <= 0
    out = out.at[..., 0].set(jnp.where(sup, -1.0, out[..., 0]))
    return out


# ----------------------------------------------------------------- roi


@register("ROIAlign", aliases=("_contrib_ROIAlign",))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=False, **_):
    """ROI Align with bilinear sampling (reference: contrib/roi_align.cc).

    data: (B, C, H, W); rois: (R, 5) [batch_idx, x1, y1, x2, y2].
    """
    ph, pw = pooled_size
    sr = max(int(sample_ratio), 1)
    off = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale - off, roi[2] * spatial_scale - off, \
            roi[3] * spatial_scale - off, roi[4] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: (ph*sr, pw*sr)
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * (bin_h / sr)
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * (bin_w / sr)
        img = data[bidx]                                  # (C, H, W)
        c, hh, ww = img.shape
        yc = jnp.clip(ys, 0, hh - 1)
        xc = jnp.clip(xs, 0, ww - 1)
        y0 = jnp.floor(yc).astype(jnp.int32)
        x0 = jnp.floor(xc).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, hh - 1)
        x1i = jnp.minimum(x0 + 1, ww - 1)
        wy = yc - y0
        wx = xc - x0
        a = img[:, y0][:, :, x0]
        bq = img[:, y0][:, :, x1i]
        cq = img[:, y1i][:, :, x0]
        d = img[:, y1i][:, :, x1i]
        samp = (a * (1 - wy)[None, :, None] * (1 - wx)[None, None, :] +
                bq * (1 - wy)[None, :, None] * wx[None, None, :] +
                cq * wy[None, :, None] * (1 - wx)[None, None, :] +
                d * wy[None, :, None] * wx[None, None, :])
        samp = samp.reshape(c, ph, sr, pw, sr)
        pooled = samp.mean(axis=(2, 4))                  # (C, ph, pw)
        if position_sensitive:
            # PS-ROIAlign (reference roi_align.cc position_sensitive):
            # C = C_out * ph * pw; bin (i, j) of output channel k reads
            # input channel k*ph*pw + i*pw + j
            c_out = c // (ph * pw)
            ps = pooled.reshape(c_out, ph, pw, ph, pw)
            ii = jnp.arange(ph)
            jj = jnp.arange(pw)
            pooled = ps[:, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]
        return pooled

    return jax.vmap(one_roi)(rois)


# ----------------------------------------------------------------- resize/pool


@register("BilinearResize2D", aliases=("_contrib_BilinearResize2D",))
def bilinear_resize2d(data, height=1, width=1, scale_height=None,
                      scale_width=None, mode="size", align_corners=True, **_):
    """reference: contrib/bilinear_resize.cc"""
    b, c, h, w = data.shape
    if scale_height is not None and mode != "size":
        height = int(h * scale_height)
        width = int(w * scale_width)
    oh, ow = int(height), int(width)
    if align_corners and oh > 1 and ow > 1:
        ys = jnp.linspace(0.0, h - 1.0, oh)
        xs = jnp.linspace(0.0, w - 1.0, ow)
    else:
        ys = jnp.clip((jnp.arange(oh) + 0.5) * h / oh - 0.5, 0, h - 1)
        xs = jnp.clip((jnp.arange(ow) + 0.5) * w / ow - 0.5, 0, w - 1)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs - x0)[None, None, None, :]
    a = data[:, :, y0][:, :, :, x0]
    bq = data[:, :, y0][:, :, :, x1]
    cq = data[:, :, y1][:, :, :, x0]
    d = data[:, :, y1][:, :, :, x1]
    return (a * (1 - wy) * (1 - wx) + bq * (1 - wy) * wx +
            cq * wy * (1 - wx) + d * wy * wx).astype(data.dtype)


@register("AdaptiveAvgPooling2D", aliases=("_contrib_AdaptiveAvgPooling2D",))
def adaptive_avg_pooling2d(data, output_size=(1, 1), **_):
    """reference: contrib/adaptive_avg_pooling.cc"""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    if len(output_size) == 1:
        output_size = (output_size[0], output_size[0])
    oh, ow = int(output_size[0]), int(output_size[1])
    b, c, h, w = data.shape
    if h % oh == 0 and w % ow == 0:
        return data.reshape(b, c, oh, h // oh, ow, w // ow).mean(axis=(3, 5))
    # general case: per output cell average over [floor(i*h/oh), ceil((i+1)h/oh))
    ys = [(int(i * h // oh), int(-(-((i + 1) * h) // oh))) for i in range(oh)]
    xs = [(int(j * w // ow), int(-(-((j + 1) * w) // ow))) for j in range(ow)]
    rows = []
    for y0, y1 in ys:
        cols = [data[:, :, y0:y1, x0:x1].mean(axis=(2, 3)) for x0, x1 in xs]
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


# ----------------------------------------------------------------- masking


@register("boolean_mask", aliases=("_contrib_boolean_mask",))
def boolean_mask(data, index, axis=0, **_):
    """Fixed-capacity boolean_mask (reference: contrib/boolean_mask.cc).

    The reference output shape is data-dependent (#nonzero); XLA needs
    static shapes, so selected rows are compacted to the front and the
    buffer keeps its full length, padded with zeros — consumers mask by
    the returned count convention (row i valid iff i < index.sum()).
    """
    ax = int(axis)
    mask = index.astype(bool)
    n = data.shape[ax]
    moved = jnp.moveaxis(data, ax, 0)
    # stable compaction permutation: selected indices first
    order = jnp.argsort(~mask, stable=True)
    compacted = moved[order]
    valid = jnp.arange(n) < mask.sum()
    shape = (n,) + (1,) * (compacted.ndim - 1)
    out = jnp.where(valid.reshape(shape), compacted, 0)
    return jnp.moveaxis(out, 0, ax)


@register("quadratic", aliases=("_contrib_quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0, **_):
    """Tutorial op (reference: contrib/quadratic_op.cc)."""
    return a * data * data + b * data + c


@register("arange_like", aliases=("_contrib_arange_like",))
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, **_):
    """Arithmetic sequence shaped like ``data`` (or along one axis),
    each value repeated ``repeat`` times — a shape-polymorphic arange
    (reference: contrib RangeLikeParam, tensor/init_op.cc)."""
    r = max(int(repeat), 1)
    if axis is None:
        n = data.size
        # each value repeated `repeat` times (reference RangeLikeParam)
        out = start + step * (jnp.arange(n) // r).astype(data.dtype)
        return out.reshape(data.shape)
    n = data.shape[int(axis)]
    return start + step * (jnp.arange(n) // r).astype(data.dtype)


@register("getnnz", aliases=("_contrib_getnnz",))
def getnnz(data, axis=None, **_):
    """Count of nonzero elements, total or per ``axis`` (reference:
    contrib/nnz.cc over CSR storage; dense count here — storage is an
    XLA layout concern on TPU)."""
    return (data != 0).sum(axis=axis).astype(jnp.int64)


@register("_contrib_SyncBatchNorm", aliases=("SyncBatchNorm",),
          num_outputs=1)
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    ndev=1, key="", axis_name=None, **_):
    """Cross-device BatchNorm (reference: contrib/sync_batch_norm.cc:48 —
    the op whose stats reduction is a communication barrier across GPUs).

    Delegates to the ONE BatchNorm implementation (ops/nn.py) with
    ``axis_name`` set: under GSPMD jit a plain BatchNorm over a
    batch-sharded tensor already reduces globally, so the pmean matters
    only for explicit per-device parallelism (shard_map/pmap)."""
    from .nn import batch_norm

    return batch_norm(data, gamma, beta, moving_mean, moving_var, eps=eps,
                      momentum=momentum, fix_gamma=fix_gamma,
                      use_global_stats=use_global_stats, axis=1,
                      axis_name=axis_name)


@register("khatri_rao", aliases=("_contrib_krprod",))
def khatri_rao(*matrices, **_):
    """Column-wise Khatri-Rao product (reference: contrib/krprod.cc):
    inputs (k_i, r) share the column count r; output is
    (prod(k_i), r) with column j the Kronecker product of the
    corresponding input columns."""
    out = matrices[0]
    for m in matrices[1:]:
        r = out.shape[1]
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, r)
    return out
