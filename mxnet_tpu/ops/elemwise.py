"""Elementwise unary/binary/scalar/broadcast operator families.

Reference: src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_op_basic.cc, elemwise_binary_broadcast_op_basic.cc,
elemwise_binary_scalar_op_*.cc — macro-generated families; here they are
generated from tables of jnp callables.  XLA fuses chains of these into
single kernels, which replaces the reference's manual kernel bulking
(src/executor/graph_executor.cc:1187 InitOpSegs).

MXNet distinguishes ``elemwise_*`` (same-shape) from ``broadcast_*``
(numpy broadcasting); XLA handles both identically, so both names map to
the same fused implementation and we keep the distinction only in the
registered names for API parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# --------------------------------------------------------------------------
# unary family (reference: elemwise_unary_op_basic.cc, *_trig.cc, *_logexp.cc)
# --------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,      # fix == round-toward-zero; jnp.fix is deprecated
    "round": jnp.round,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma,
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "isnan": jnp.isnan,
    "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
}


def _register_unary(name, f):
    @register(name, aliases=("_npi_" + name,))
    def _op(x, **_):
        """Elementwise unary op, generated from the _UNARY table."""
        return f(x)

    _op.__name__ = name
    _op.__doc__ = (
        "Elementwise %s(x), applied per element (generated from the "
        "_UNARY table; reference: the elemwise_unary_op_basic.cc / "
        "*_trig.cc / *_logexp.cc macro families).  XLA fuses chains "
        "of these into single kernels." % name)
    return _op


for _n, _f in _UNARY.items():
    _register_unary(_n, _f)


@register("softrelu")
def softrelu(x, **_):
    """Soft-ReLU activation log(1+exp(x)), numerically stable
    (reference: mshadow_op::softrelu)."""
    return jax.nn.softplus(x)


@register("hard_sigmoid")
def hard_sigmoid(x, alpha=0.2, beta=0.5, **_):
    """Piecewise-linear sigmoid clip(alpha*x + beta, 0, 1)
    (reference: hard_sigmoid-inl.h)."""
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register("clip")
def clip(x, a_min=None, a_max=None, **_):
    """Clamp every element into [a_min, a_max]
    (reference: tensor/matrix_op.cc clip)."""
    return jnp.clip(x, a_min, a_max)


@register("Cast", aliases=("cast",))
def cast(x, dtype="float32", **_):
    """Element type conversion to ``dtype``
    (reference: elemwise_unary_op_basic.cc Cast)."""
    from ..base import np_dtype

    return x.astype(np_dtype(dtype))


@register("_copy", aliases=("identity",))
def identity(x, **_):
    """Identity / copy (reference: elemwise_unary_op_basic.cc _copy);
    XLA elides the no-op under jit."""
    return x


@register("BlockGrad", aliases=("stop_gradient", "block_grad"))
def stop_gradient(x, **_):
    """Identity forward, zero gradient backward
    (reference: BlockGrad, elemwise_unary_op_basic.cc)."""
    return lax.stop_gradient(x)


@register("make_loss", aliases=("MakeLoss",))
def make_loss(x, **_):
    """Mark an output as a loss head: identity value whose gradient
    seeds backward with ones (reference: make_loss, MakeLoss op)."""
    return x


# --------------------------------------------------------------------------
# binary family — elemwise_* (same shape) and broadcast_* (numpy broadcast)
# --------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: (a == b),
    "not_equal": lambda a, b: (a != b),
    "greater": lambda a, b: (a > b),
    "greater_equal": lambda a, b: (a >= b),
    "lesser": lambda a, b: (a < b),
    "lesser_equal": lambda a, b: (a <= b),
    "logical_and": lambda a, b: jnp.logical_and(a != 0, b != 0),
    "logical_or": lambda a, b: jnp.logical_or(a != 0, b != 0),
    "logical_xor": lambda a, b: jnp.logical_xor(a != 0, b != 0),
}

_BOOL_RESULT = {
    "equal", "not_equal", "greater", "greater_equal", "lesser", "lesser_equal",
    "logical_and", "logical_or", "logical_xor",
}


def _register_binary(name, f):
    bool_out = name in _BOOL_RESULT

    def _impl(a, b, **_):
        """Elementwise binary op, generated from the _BINARY table."""
        out = f(a, b)
        if bool_out:
            # reference returns same-dtype 0/1 tensors, not bools
            out = out.astype(jnp.result_type(a, b))
        return out

    _impl.__name__ = "elemwise_%s" % name
    _impl.__doc__ = (
        "Elementwise %s(lhs, rhs), registered both as elemwise_%s "
        "(same-shape) and broadcast_%s (numpy broadcasting) — XLA "
        "handles both identically (generated from the _BINARY table; "
        "reference: elemwise_binary_op_basic.cc / "
        "elemwise_binary_broadcast_op_basic.cc).%s"
        % (name, name, name,
           "  Comparison/logical results are same-dtype 0/1 tensors, "
           "not bools, matching the reference." if bool_out else ""))
    register("elemwise_%s" % name, aliases=("_%s" % name,))(_impl)
    register("broadcast_%s" % name)(_impl)
    return _impl


for _n, _f in _BINARY.items():
    _register_binary(_n, _f)


@register("_scatter_elemwise_div")
def scatter_elemwise_div(a, b, **_):
    """Elementwise division with sparse-aware storage in the reference
    (elemwise_binary_op_basic.cc _scatter_elemwise_div); dense here."""
    return a / b


# --------------------------------------------------------------------------
# scalar family (reference: elemwise_binary_scalar_op_*.cc)
# --------------------------------------------------------------------------

_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, s),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_logical_and_scalar": lambda x, s: jnp.logical_and(x != 0, s != 0).astype(x.dtype),
    "_logical_or_scalar": lambda x, s: jnp.logical_or(x != 0, s != 0).astype(x.dtype),
    "_logical_xor_scalar": lambda x, s: jnp.logical_xor(x != 0, s != 0).astype(x.dtype),
}


def _register_scalar(name, f):
    # scalar is traced: eager `x * python_float` with a per-step value
    # (scheduler lr in composite optimizer loops) must not recompile
    @register(name, traced_attrs=("scalar",))
    def _op(x, scalar=0.0, **_):
        """Tensor-scalar elementwise op, from the _SCALAR table."""
        return f(x, scalar)

    _op.__name__ = name
    _op.__doc__ = (
        "%s(x, scalar=...) applied per element, with the scalar passed "
        "as a TRACED attr so per-step values (e.g. a scheduled lr) "
        "never recompile (generated from the _SCALAR table; reference: "
        "the elemwise_binary_scalar_op_*.cc macro family)." % name)
    return _op


for _n, _f in _SCALAR.items():
    _register_scalar(_n, _f)


@register("smooth_l1", traced_attrs=("scalar",))
def smooth_l1(x, scalar=1.0, **_):
    """Smooth-L1 (Huber) loss with sigma=scalar
    (reference: mshadow_op::smooth_l1_loss)."""
    s2 = scalar * scalar
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)


# --------------------------------------------------------------------------
# n-ary / misc
# --------------------------------------------------------------------------


@register("add_n", aliases=("ElementWiseSum", "_sum_multi"))
def add_n(*args, **_):
    """Sum of N same-shape tensors in one fused kernel — the kvstore
    push-reduce primitive (reference: ElementWiseSumCompute)."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("where")
def where(condition, x, y, **_):
    """Select x where condition is nonzero else y; a 1-D condition
    selects whole rows (reference: control_flow_op.cc where)."""
    if condition.ndim < x.ndim and condition.ndim == 1:
        # reference allows 1-D condition selecting rows
        shape = (condition.shape[0],) + (1,) * (x.ndim - 1)
        condition = condition.reshape(shape)
    return jnp.where(condition != 0, x, y)
