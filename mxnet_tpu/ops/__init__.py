"""Operator library: importing this package registers all operators.

Layout mirrors the reference src/operator/ split (SURVEY.md §2.1):
elemwise/reduce/matrix ≈ src/operator/tensor/, nn ≈ src/operator/nn/,
init_ops+random ≈ init_op.cc + src/operator/random/, optimizer_ops ≈
optimizer_op.cc, rnn_ops ≈ rnn.cc (via lax.scan), control_flow ≈
control_flow.cc, contrib ≈ src/operator/contrib/.
"""

from . import registry  # noqa: F401
from . import elemwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import nn  # noqa: F401
from . import init_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn  # noqa: F401
from . import contrib  # noqa: F401
from . import attention  # noqa: F401
from . import custom  # noqa: F401
from . import quantization  # noqa: F401
from . import linalg  # noqa: F401
from . import extended  # noqa: F401

from .registry import apply_op, get, list_ops, register  # noqa: F401
