"""Fused multi-head attention — Pallas TPU flash-attention kernels.

The reference framework has no fused attention; its transformer helpers
(`src/operator/contrib/transformer.cc`: interleaved_matmul_selfatt_qk /
valatt, div_sqrt_dim) materialise the full (seq, seq) score matrix in
HBM.  On TPU that is HBM-bandwidth-bound; the TPU-native design is a
flash-attention kernel that tiles Q/K/V through VMEM, keeps the online
softmax statistics in VMEM scratch across the (sequential) K-block grid
steps, and feeds the MXU with (block_q x d) @ (d x block_k) matmuls.

Layout: (batch, heads, seq, head_dim) throughout.

Public entry points
-------------------
flash_attention(q, k, v, causal=..., sm_scale=...)  — custom_vjp fused op
registered ops: ``_contrib_flash_attention`` plus the reference transformer
helper ops (``_contrib_div_sqrt_dim``, interleaved matmul family).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .registry import register

# Measured on v5e (tools/bench_attention.py, r3): 256/512 blocks run
# the fwd kernel ~2.9x faster than 128/128 (6.1 -> 17.6 TFLOP/s at
# seq 4096, d=64) — larger K blocks amortize the online-softmax
# rescale and keep the MXU busy despite the narrow d=64 operand.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# reference (unfused) implementation — also the CPU / odd-shape fallback
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, causal=False, sm_scale=None):
    """Unfused attention: softmax(q k^T * scale) v, fp32 accumulation."""
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if sm_scale is None else sm_scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (qlen, klen), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (qlen, klen), 1)
        s = jnp.where(col > row, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal,
                block_q, block_k, num_k):
    """Grid = (batch*heads, num_q, num_k); K is the innermost (sequential)
    axis so the VMEM scratch (acc, m, l) carries across K steps."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip blocks strictly above the diagonal
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32)            # (block_q, d)
        kb = k_ref[0].astype(jnp.float32)           # (block_k, d)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row, _NEG_INF, s)

        m_prev = m_ref[:, 0:1]                       # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                       # (bq, bk)
        corr = jnp.exp(m_prev - m_new)               # (bq, 1)
        l_new = l_ref[:, 0:1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        vb = v_ref[0].astype(jnp.float32)            # (block_k, d)
        pv = jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == num_k - 1)
    def _():
        l = l_ref[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)              # fully-masked rows
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # lse rides as (bh, sq, 1): a (block_q, 1) block keeps the TPU
        # (8, 128)-tiling rule satisfied (last dim == full array dim)
        lse_ref[0] = m_ref[:, 0:1] + jnp.log(l)


def _fwd_pallas(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)
    num_q = sq // block_q
    num_k = sk // block_k

    kern = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k)
    out, lse = pl.pallas_call(
        kern,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda z, i, j: (z, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda z, i, j: (z, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda z, i, j: (z, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda z, i, j: (z, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda z, i, j: (z, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, sm_scale, causal, block_q, block_k, num_k):
    """Grid = (bh, num_q, num_k): accumulate dq over K blocks."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                             # (bq, 1)
        delta = delta_ref[0]                         # (bq, 1)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row, _NEG_INF, s)
        p = jnp.exp(s - lse)                         # softmax probs
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        acc_ref[:] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_k - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, sm_scale, causal, block_q, block_k, num_q):
    """Grid = (bh, num_k, num_q): accumulate dk/dv over Q blocks."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= kj * block_k

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                             # (bq, 1)
        delta = delta_ref[0]                         # (bq, 1)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row, _NEG_INF, s)
        p = jnp.exp(s - lse)                         # (bq, bk)
        # dv += p^T @ do
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale             # (bq, bk)
        # dk += ds^T @ q
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, sm_scale, causal,
                block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    qr, kr, vr = (x.reshape(bh, -1, d) for x in (q, k, v))
    dor = do.reshape(bh, sq, d)
    lser = lse.reshape(bh, sq, 1)
    # delta_i = rowsum(dO_i * O_i) — tiny elementwise pass, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(bh, sq, 1)
    num_q = sq // block_q
    num_k = sk // block_k

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k=num_k),
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda z, i, j: (z, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda z, i, j: (z, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda z, i, j: (z, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda z, i, j: (z, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda z, i, j: (z, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda z, i, j: (z, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda z, i, j: (z, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=num_q),
        grid=(bh, num_k, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda z, j, i: (z, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda z, j, i: (z, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda z, j, i: (z, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda z, j, i: (z, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda z, j, i: (z, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda z, j, i: (z, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda z, j, i: (z, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda z, j, i: (z, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    return (dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape))


# ---------------------------------------------------------------------------
# public fused op (custom_vjp) with automatic fallback
# ---------------------------------------------------------------------------

def _use_pallas(q, k, v, block_q, block_k, interpret):
    # interpret mode bypasses only the backend check: the kernel's grid
    # still assumes the blocks tile the sequence exactly, so a ragged
    # seq (e.g. 300 with 256-blocks) would leave trailing rows unwritten
    # in interpret mode just as on hardware
    if not interpret and jax.default_backend() != "tpu":
        return False
    sq, sk = q.shape[2], k.shape[2]
    return sq % block_q == 0 and sk % block_k == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _fwd_pallas(q, k, v, sm_scale, causal, block_q, block_k,
                         interpret)
    return out


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd_pallas(q, k, v, sm_scale, causal, block_q, block_k,
                           interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _bwd_pallas(q, k, v, out, lse, g, sm_scale, causal,
                       block_q, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    """Fused attention over (batch, heads, seq, head_dim) arrays.

    Pallas flash kernel on TPU (or with interpret=True anywhere);
    falls back to the XLA-fused reference off-TPU or for ragged shapes.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    # prefer the fast measured blocks, but step down to 128/128 for
    # sequences they don't divide before abandoning the fused path
    for cq, ck in ((block_q, block_k), (128, 128)):
        bq = min(cq, q.shape[2])
        bk = min(ck, k.shape[2])
        if _use_pallas(q, k, v, bq, bk, interpret):
            return _flash(q, k, v, sm_scale, causal, bq, bk, interpret)
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)


# pallas imports are deferred so that `import mxnet_tpu` works on builds
# without pallas; resolved at first use
try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pl = None
    pltpu = None


# ---------------------------------------------------------------------------
# registered ops (reference: src/operator/contrib/transformer.cc)
# ---------------------------------------------------------------------------

@register("_contrib_flash_attention", aliases=("flash_attention",))
def flash_attention_op(query, key, value, causal=False, sm_scale=None, **_):
    """Fused scaled-dot-product attention over (B, H, T, D) q/k/v —
    the registry face of :func:`flash_attention` (tiled online-softmax
    kernel; ``causal`` masks the upper triangle, ``sm_scale`` defaults
    to 1/sqrt(D))."""
    return flash_attention(query, key, value, causal=bool(causal),
                           sm_scale=sm_scale)


@register("_contrib_div_sqrt_dim", aliases=("div_sqrt_dim",))
def div_sqrt_dim(data, **_):
    """data / sqrt(last_dim) (src/operator/contrib/transformer.cc)."""
    return data / math.sqrt(data.shape[-1])


@register("_contrib_interleaved_matmul_selfatt_qk",
          aliases=("interleaved_matmul_selfatt_qk",))
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1, **_):
    """Scores from interleaved qkv (seq, batch, 3*proj) layout.

    Reference computes q k^T from the packed projection
    (src/operator/contrib/transformer.cc interleaved_matmul_selfatt_qk).
    Output: (batch*heads, seq, seq).
    """
    s, b, p3 = queries_keys_values.shape
    proj = p3 // 3
    d = proj // heads
    x = queries_keys_values.reshape(s, b, heads, 3, d)
    q = x[:, :, :, 0, :]
    k = x[:, :, :, 1, :]
    # (b*h, s, d) @ (b*h, d, s)
    qt = q.transpose(1, 2, 0, 3).reshape(b * heads, s, d)
    kt = k.transpose(1, 2, 0, 3).reshape(b * heads, s, d)
    return jnp.einsum("zqd,zkd->zqk", qt, kt,
                      preferred_element_type=jnp.float32).astype(
                          queries_keys_values.dtype)


@register("_contrib_interleaved_matmul_selfatt_valatt",
          aliases=("interleaved_matmul_selfatt_valatt",))
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads=1, **_):
    """attention @ values back to (seq, batch, proj) layout."""
    s, b, p3 = queries_keys_values.shape
    proj = p3 // 3
    d = proj // heads
    x = queries_keys_values.reshape(s, b, heads, 3, d)
    v = x[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(b * heads, s, d)
    out = jnp.einsum("zqk,zkd->zqd", attention.astype(jnp.float32),
                     v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(b, heads, s, d).transpose(2, 0, 1, 3).reshape(
        s, b, proj).astype(queries_keys_values.dtype)
