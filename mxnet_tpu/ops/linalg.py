"""Linear-algebra operator additions (reference: src/operator/tensor/
la_op.cc).  The core set (gemm/gemm2/potrf/potri/trmm/trsm/syrk/
sumlogdiag/extractdiag/makediag) lives in matrix.py; this module adds
the two missing factorizations and the reference's underscore aliases
(`_linalg_*`, the registered nnvm names)."""

from __future__ import annotations

import jax.numpy as jnp

from .registry import alias, register


@register("linalg_syevd", aliases=("_linalg_syevd",), num_outputs=2)
def linalg_syevd(A, **_):
    """Symmetric eigendecomposition; returns (U, lambda) with
    A = Uᵀ diag(lambda) U (reference syevd: rows of U are eigenvectors)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("linalg_gelqf", aliases=("_linalg_gelqf",), num_outputs=2)
def linalg_gelqf(A, **_):
    """LQ factorization A = L Q with Q orthonormal rows (reference
    gelqf); computed via QR of Aᵀ."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


# underscore aliases for the core set registered in matrix.py
for _name in ("linalg_gemm", "linalg_gemm2", "linalg_potrf", "linalg_potri",
              "linalg_trmm", "linalg_trsm", "linalg_syrk",
              "linalg_sumlogdiag", "linalg_extractdiag", "linalg_makediag"):
    alias("_" + _name, _name)
