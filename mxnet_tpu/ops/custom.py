"""`Custom` as a first-class registry op — symbolic/staged custom ops.

Reference: src/operator/custom/custom.cc:103 runs user Python callbacks
on a dedicated thread pool so they compose with the async engine.  The
TPU-native analog: the user's ``CustomOp.forward``/``backward`` run as
``jax.pure_callback`` host calls inside the XLA program, wrapped in a
``jax.custom_vjp`` so gradients route through the user's ``backward``.
This makes ``mx.sym.Custom(..., op_type=...)`` and custom ops inside
hybridized Gluon blocks work exactly like the eager ``mx.nd.Custom``.
"""

from __future__ import annotations

import numpy as _np

from .registry import register


def _prop_for(op_type, kwargs):
    from ..operator import get_custom_op

    return get_custom_op(op_type)(**{k: str(v) for k, v in kwargs.items()})


def _custom_nout(attrs):
    attrs = dict(attrs)
    op_type = attrs.pop("op_type", None)
    if op_type is None:
        return 1
    try:
        return len(_prop_for(op_type, attrs).list_outputs())
    except Exception:
        return 1


@register("Custom", num_outputs=_custom_nout)
def custom(*arrays, op_type=None, **kwargs):
    """Run a user-registered CustomOp (``mx.operator.register``) named
    ``op_type`` — a deliberate host-side escape hatch: inputs are
    materialized for the python forward, so this op is never fused and
    never jitted (reference: operator/custom/custom.cc)."""
    import jax

    from .. import ndarray as nd_mod
    from ..base import MXNetError

    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    prop = _prop_for(op_type, kwargs)
    in_shapes = [tuple(a.shape) for a in arrays]
    in_types = [_np.dtype(a.dtype) for a in arrays]
    _, out_shapes, aux_shapes = prop.infer_shape([list(s) for s in in_shapes])
    try:
        _, out_types, _ = prop.infer_type(list(in_types))
    except Exception:
        out_types = [in_types[0]] * len(out_shapes)
    out_types = [_np.dtype(t) for t in out_types]
    op = prop.create_operator(None, in_shapes, in_types)
    n_in, n_out = len(arrays), len(out_shapes)

    def _nds(np_arrays, shapes=None, dtypes=None):
        if shapes is None:
            return [nd_mod.array(_np.asarray(a)) for a in np_arrays]
        return [nd_mod.zeros(tuple(s), dtype=t)
                for s, t in zip(shapes, dtypes)]

    def host_fwd(*np_ins):
        in_nds = _nds(np_ins)
        outs = _nds(None, out_shapes, out_types)
        aux = _nds(None, aux_shapes, [in_types[0]] * len(aux_shapes))
        op.forward(True, ["write"] * n_out, in_nds, outs, aux)
        # CustomOp's contract IS a host callback (numpy in, numpy out)
        # and pure_callback already left the device; no hidden sync here
        return tuple(_np.asarray(o.asnumpy(), dtype=t)  # mxlint: disable=trace-host-sync
                     for o, t in zip(outs, out_types))

    fwd_spec = tuple(jax.ShapeDtypeStruct(tuple(s), t)
                     for s, t in zip(out_shapes, out_types))

    @jax.custom_vjp
    def f(*arrs):
        return jax.pure_callback(host_fwd, fwd_spec, *arrs)

    def f_fwd(*arrs):
        outs = jax.pure_callback(host_fwd, fwd_spec, *arrs)
        return outs, (arrs, outs)

    def f_bwd(res, gs):
        arrs, outs = res

        def host_bwd(*flat):
            in_nds = _nds(flat[:n_in])
            out_nds = _nds(flat[n_in:n_in + n_out])
            grad_nds = _nds(flat[n_in + n_out:])
            in_grads = _nds(None, in_shapes, in_types)
            aux = _nds(None, aux_shapes, [in_types[0]] * len(aux_shapes))
            op.backward(["write"] * n_in, grad_nds, in_nds, out_nds,
                        in_grads, aux)
            # same host-bridge contract as host_fwd above
            return tuple(_np.asarray(g.asnumpy(), dtype=t)  # mxlint: disable=trace-host-sync
                         for g, t in zip(in_grads, in_types))

        bwd_spec = tuple(jax.ShapeDtypeStruct(tuple(s), t)
                         for s, t in zip(in_shapes, in_types))
        return jax.pure_callback(host_bwd, bwd_spec, *arrs, *outs, *gs)

    f.defvjp(f_fwd, f_bwd)
    res = f(*arrays)
    return res if n_out > 1 else res[0]


_SUBGRAPH_CACHE = {}


def _subgraph_nout(attrs):
    return int(attrs.get("num_outputs", 1))


@register("_subgraph_exec", num_outputs=_subgraph_nout)
def subgraph_exec(*arrays, subgraph_json=None, num_outputs=1, **_):
    """Execute a captured region as one staged callee (reference:
    subgraph ops created by CreateSubgraphNode; here the region stages
    through the jit cache and XLA fuses it).  Positional inputs bind to
    the serialized sub-symbol's arguments in declaration order."""
    from ..base import MXNetError

    if subgraph_json is None:
        raise MXNetError("_subgraph_exec requires subgraph_json=")
    entry = _SUBGRAPH_CACHE.get(subgraph_json)
    if entry is None:
        from ..executor import make_eval_fn
        from ..symbol import load_json

        sub = load_json(subgraph_json)
        fn = make_eval_fn(sub, is_train=False)
        fn = fn[0] if isinstance(fn, tuple) else fn
        # positional inputs arrive in list_inputs() order (the wrapper's
        # contract); the callee wants (args, aux) split by name.  The
        # default partitioner only captures pure ops, so aux lists are
        # normally empty — the split handles custom wrappers that carry
        # aux-feeding placeholders anyway.
        entry = (fn, sub.list_inputs(), sub.list_arguments(),
                 sub.list_auxiliary_states())
        _SUBGRAPH_CACHE[subgraph_json] = entry
    fn, in_names, arg_names, aux_names = entry
    by_name = dict(zip(in_names, arrays))
    outs, _aux = fn([by_name[n] for n in arg_names],
                    [by_name[n] for n in aux_names], 0)
    outs = tuple(outs)
    return outs if len(outs) > 1 else outs[0]
