"""Pallas TPU kernel for max-pooling backward (NHWC).

Why: the flagship step's maxpool-backward lowers to XLA
select-and-scatter at ~0.1% MXU and 66% of the bandwidth roofline
(BENCH_ROOFLINE.md: 765 us vs a 502 us byte bound) — pure data
movement with headroom.  The TPU-native formulation is gather-style:
one pass computes each window's FIRST argmax (XLA's select tie-break)
from strided tap slices held in VMEM, then scatters dY through nine
strided read-modify-writes of the VMEM-resident output block — HBM
sees x, dy and dx exactly once per image block.

Layout: NHWC; symmetric padding (the 'valid' pooling convention);
the pad region of x is filled with -inf so it never wins a max.
`supported()` gates shapes; callers fall back to XLA's lowering.
Reference analog: the backward kernels behind
src/operator/nn/pooling.cc (cuDNN PoolingBackward).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from .pallas_conv import _VMEM_BUDGET, _block_images, _pad_to

_NEG = float("-inf")


def supported(x_shape, dy_shape, kernel, stride, pad, ebytes=2):
    if not _HAS_PALLAS or len(kernel) != 2:
        return False
    n, h, w, c = x_shape
    _, oh, ow, dc = dy_shape
    if c != dc or c < 8:
        return False
    if (h + 2 * pad[0] - kernel[0]) // stride[0] + 1 != oh:
        return False
    if (w + 2 * pad[1] - kernel[1]) // stride[1] + 1 != ow:
        return False
    hp, wp = h + 2 * pad[0], w + 2 * pad[1]
    per_image = (2 * hp * _pad_to(wp, 8) * _pad_to(c, 128) +
                 2 * oh * _pad_to(ow, 8) * _pad_to(c, 128)) * ebytes
    return per_image <= _VMEM_BUDGET


def _bwd_kernel(x_ref, dy_ref, out_ref, *, kh, kw, sy, sx, oh, ow):
    out_ref[:] = jnp.zeros_like(out_ref)
    m = None
    idx = None
    for t in range(kh * kw):
        r, c = divmod(t, kw)
        v = x_ref[:, r:r + sy * oh:sy, c:c + sx * ow:sx, :]
        if m is None:
            m = v
            idx = jnp.zeros(v.shape, jnp.int32)
        else:
            take = v > m  # strict: ties keep the EARLIER tap (XLA select)
            m = jnp.where(take, v, m)
            idx = jnp.where(take, t, idx)
    dy = dy_ref[:]
    zero = jnp.zeros_like(dy)
    for t in range(kh * kw):
        r, c = divmod(t, kw)
        contrib = jnp.where(idx == t, dy, zero)
        cur = out_ref[:, r:r + sy * oh:sy, c:c + sx * ow:sx, :]
        out_ref[:, r:r + sy * oh:sy, c:c + sx * ow:sx, :] = cur + contrib


@functools.partial(jax.jit,
                   static_argnames=("kernel", "stride", "pad", "interpret"))
def maxpool_bwd_nhwc(x, dy, kernel, stride, pad=(0, 0), interpret=False):
    """dX for NHWC max pooling: x (N,H,W,C) forward input, dy the
    (N,OH,OW,C) cotangent; returns (N,H,W,C) in dy.dtype."""
    kh, kw = kernel
    sy, sx = stride
    n, h, w, c = x.shape
    _, oh, ow, _c = dy.shape
    if not interpret:
        interpret = jax.default_backend() != "tpu"
    xp = jnp.pad(x, ((0, 0), (pad[0], pad[0]), (pad[1], pad[1]), (0, 0)),
                 constant_values=_NEG)
    hp, wp = xp.shape[1], xp.shape[2]

    ebytes = max(x.dtype.itemsize, dy.dtype.itemsize)
    per_image = (2 * hp * _pad_to(wp, 8) * _pad_to(c, 128) +
                 2 * oh * _pad_to(ow, 8) * _pad_to(c, 128)) * ebytes
    nb = _block_images(n, per_image, 0)

    kern = functools.partial(_bwd_kernel, kh=kh, kw=kw, sy=sy, sx=sx,
                             oh=oh, ow=ow)
    dxp = pl.pallas_call(
        kern,
        grid=(n // nb,),
        in_specs=[
            pl.BlockSpec((nb, hp, wp, c), lambda g: (g, 0, 0, 0)),
            pl.BlockSpec((nb, oh, ow, c), lambda g: (g, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((nb, hp, wp, c), lambda g: (g, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, hp, wp, c), dy.dtype),
        interpret=interpret,
    )(xp, dy)
    return dxp[:, pad[0]:pad[0] + h, pad[1]:pad[1] + w, :]
