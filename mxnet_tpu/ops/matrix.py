"""Shape-manipulation, linear-algebra and indexing operators.

Reference: src/operator/tensor/matrix_op.cc (reshape/transpose/slice/
concat/stack/split/pad/tile/repeat/flip/...), dot.cc, ordering_op.cc
(sort/topk/argsort), indexing_op.cc (take/one_hot/gather_nd/scatter_nd/
Embedding), la_op.cc (linalg_*).

MXNet dot on >2-D operates on the flattened trailing/leading dims — kept
here.  ``dot``/``batch_dot`` lower to XLA dot_general → the TPU MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# ---------------------------------------------------------------- reshape etc.


@register("Reshape", aliases=("reshape",))
def reshape(x, shape=(), reverse=False, **_):
    """MXNet reshape with special codes 0 (copy dim), -1 (infer),
    -2 (copy rest), -3 (merge two dims), -4 (split dim)."""
    src = list(x.shape[::-1]) if reverse else list(x.shape)
    tgt_spec = list(shape[::-1]) if reverse else list(shape)
    out = []
    src_i = 0
    i = 0
    while i < len(tgt_spec):
        s = tgt_spec[i]
        if s == 0:
            out.append(src[src_i])
            src_i += 1
        elif s == -1:
            out.append(-1)
            src_i += 1
        elif s == -2:
            out.extend(src[src_i:])
            src_i = len(src)
        elif s == -3:
            out.append(src[src_i] * src[src_i + 1])
            src_i += 2
        elif s == -4:
            d1, d2 = tgt_spec[i + 1], tgt_spec[i + 2]
            if d1 == -1:
                d1 = src[src_i] // d2
            if d2 == -1:
                d2 = src[src_i] // d1
            out.extend([d1, d2])
            src_i += 1
            i += 2
        else:
            out.append(s)
            src_i += 1
        i += 1
    if reverse:
        out = out[::-1]
    return x.reshape(tuple(out))


@register("reshape_like")
def reshape_like(x, y, **_):
    """Reshape ``x`` to ``y``'s shape (element counts must match)."""
    return x.reshape(y.shape)


@register("shape_array")
def shape_array(x, **_):
    """``x``'s shape as a 1-D int64 array (shapes are static under
    tracing, so this stages as a constant)."""
    return jnp.asarray(x.shape, dtype=jnp.int64)


@register("size_array")
def size_array(x, **_):
    """``x``'s element count as a 1-element int64 array."""
    return jnp.asarray([x.size], dtype=jnp.int64)


@register("Flatten", aliases=("flatten",))
def flatten(x, **_):
    """Collapse all but the batch (first) axis: ``(N, ...) -> (N, -1)``."""
    return x.reshape((x.shape[0], -1))


@register("transpose")
def transpose(x, axes=(), **_):
    """Permute axes; empty ``axes`` reverses them (numpy .T semantics)."""
    if not axes:
        axes = tuple(range(x.ndim))[::-1]
    return jnp.transpose(x, axes)


@register("expand_dims")
def expand_dims(x, axis=0, **_):
    """Insert a size-1 dim at ``axis``."""
    return jnp.expand_dims(x, int(axis))


@register("squeeze")
def squeeze(x, axis=None, **_):
    """Drop size-1 dims — all of them when ``axis`` is None, else the
    listed one(s)."""
    if axis is None:
        return jnp.squeeze(x)
    return jnp.squeeze(x, axis=axis if isinstance(axis, tuple) else (int(axis),))


@register("Concat", aliases=("concat",))
def concat(*args, dim=1, **_):
    """Concatenate inputs along ``dim`` (default 1, the reference's
    channel-concat convention); accepts a single list/tuple too."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return jnp.concatenate(args, axis=int(dim))


@register("stack")
def stack(*args, axis=0, **_):
    """Stack inputs along a NEW ``axis``; accepts a single
    list/tuple too."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return jnp.stack(args, axis=int(axis))


def _split_nout(attrs):
    return int(attrs.get("num_outputs", 1))


@register("SliceChannel", aliases=("split",), num_outputs=_split_nout)
def split(x, num_outputs=1, axis=1, squeeze_axis=False, **_):
    """Split ``x`` into ``num_outputs`` equal parts along ``axis``
    (default 1, the reference's channel convention);
    ``squeeze_axis`` drops the now-size-1 split axis from each part."""
    parts = jnp.split(x, int(num_outputs), axis=int(axis))
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=int(axis)) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("slice", aliases=("crop",))
def slice_op(x, begin=(), end=(), step=(), **_):
    """N-D strided slice: per-axis ``begin``/``end``/``step`` tuples
    (None entries keep the full extent, trailing axes default open)."""
    ndim = x.ndim
    begin = tuple(begin) + (None,) * (ndim - len(begin))
    end = tuple(end) + (None,) * (ndim - len(end))
    step = tuple(step) + (None,) * (ndim - len(step)) if step else (None,) * ndim
    idx = tuple(
        builtins_slice(b, e, s if s != 0 else None)
        for b, e, s in zip(begin, end, step)
    )
    return x[idx]


builtins_slice = slice  # keep the builtin reachable under the op name


@register("slice_axis")
def slice_axis(x, axis=0, begin=0, end=None, **_):
    """Slice ``[begin, end)`` along ONE axis, all others untouched
    (``end=None`` runs to the axis's extent; negative axis wraps)."""
    axis = int(axis) % x.ndim
    idx = [builtins_slice(None)] * x.ndim
    idx[axis] = builtins_slice(begin, end)
    return x[tuple(idx)]


@register("slice_like")
def slice_like(x, y, axes=(), **_):
    """Crop ``x`` from index 0 to ``y``'s extent on the listed ``axes``
    (empty: every axis the two arrays share)."""
    axes = tuple(axes) if axes else tuple(range(min(x.ndim, y.ndim)))
    idx = [builtins_slice(None)] * x.ndim
    for a in axes:
        idx[a] = builtins_slice(0, y.shape[a])
    return x[tuple(idx)]


@register("tile")
def tile(x, reps=(), **_):
    """Repeat the whole array ``reps[i]`` times along each axis
    (numpy tile semantics)."""
    return jnp.tile(x, tuple(reps))


@register("repeat")
def repeat(x, repeats=1, axis=None, **_):
    """Repeat each ELEMENT ``repeats`` times along ``axis`` (None
    flattens first, numpy repeat semantics)."""
    return jnp.repeat(x, int(repeats), axis=None if axis is None else int(axis))


@register("reverse", aliases=("flip",))
def reverse(x, axis=(), **_):
    """Reverse element order along the given axis (or tuple of axes)."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis=axes)


@register("Pad", aliases=("pad",))
def pad(x, mode="constant", pad_width=(), constant_value=0.0, **_):
    """Pad with the reference's flat ``(before0, after0, before1, ...)``
    ``pad_width`` layout; modes: constant (with ``constant_value``),
    edge, reflect."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(x, pw, mode=jmode)


@register("space_to_depth")
def space_to_depth(x, block_size=1, **_):
    """NCHW: move each ``block_size``² spatial tile into channels —
    ``(N,C,H,W) → (N, C·b², H/b, W/b)``."""
    n, c, h, w = x.shape
    b = int(block_size)
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("depth_to_space")
def depth_to_space(x, block_size=1, **_):
    """NCHW inverse of ``space_to_depth``: redistribute channel groups
    back onto the spatial grid — ``(N,C,H,W) → (N, C/b², H·b, W·b)``."""
    n, c, h, w = x.shape
    b = int(block_size)
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


# ---------------------------------------------------------------- dot family


@register("dot")
def dot(a, b, transpose_a=False, transpose_b=False, **_):
    """MXNet dot: >2-D inputs contract last axis of a with first of b
    (after optional full transpose).  Lowers to MXU dot_general."""
    if transpose_a:
        a = jnp.transpose(a)
    if transpose_b:
        b = jnp.transpose(b)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(a, b, transpose_a=False, transpose_b=False, **_):
    """Batched matmul over the trailing two axes (leading axes are the
    batch), with optional per-operand transpose — MXU dot_general."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


# ---------------------------------------------------------------- ordering


@register("sort")
def sort(x, axis=-1, is_ascend=True, **_):
    """Sort values along ``axis`` (None flattens first);
    ``is_ascend=False`` reverses the order."""
    ax = None if axis is None else int(axis)
    out = jnp.sort(x.reshape(-1) if ax is None else x, axis=0 if ax is None else ax)
    if not is_ascend:
        out = jnp.flip(out, axis=0 if ax is None else ax)
    return out


@register("argsort")
def argsort(x, axis=-1, is_ascend=True, dtype="float32", **_):
    """Indices that would sort ``x`` along ``axis`` (None flattens),
    returned in the requested ``dtype`` (the reference's float
    default)."""
    from ..base import np_dtype

    ax = 0 if axis is None else int(axis)
    xx = x.reshape(-1) if axis is None else x
    idx = jnp.argsort(xx, axis=ax)
    if not is_ascend:
        idx = jnp.flip(idx, axis=ax)
    return idx.astype(np_dtype(dtype))


def _topk_nout(attrs):
    rt = attrs.get("ret_typ", "indices")
    return 2 if rt == "both" else 1


@register("topk", num_outputs=_topk_nout)
def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32", **_):
    """Top-``k`` along ``axis`` via ``lax.top_k``; ``ret_typ`` selects
    values / indices / a 0-1 mask / both, ``is_ascend`` picks the
    smallest-k instead, ``k<=0`` means the full axis."""
    from ..base import np_dtype

    if axis is None:
        x = x.reshape(-1)
        axis = 0
    axis = int(axis) % x.ndim
    k = int(k) if int(k) > 0 else x.shape[axis]
    xx = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(-xx if is_ascend else xx, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "mask":
        mask = jnp.zeros(xx.shape, dtype=x.dtype)
        mask = mask.at[..., :][tuple()] if False else mask  # placeholder no-op
        onehot = jax.nn.one_hot(idx.reshape(idx.shape), xx.shape[-1], dtype=x.dtype)
        mask = onehot.sum(axis=-2)
        return jnp.moveaxis(mask, -1, axis)
    idxf = idx.astype(np_dtype(dtype))
    if ret_typ == "both":
        return vals, idxf
    return idxf


# ---------------------------------------------------------------- indexing


# int32 offsets overflow inside XLA gather/scatter once an operand
# crosses 2^31 elements (the large-tensor regime, reference:
# tests/nightly/test_large_array.py); int64 indices force 64-bit offset
# arithmetic on device (emulated on TPU, correct if slower).
_INT32_SAFE_ELEMS = 2 ** 31 - 1


def _gather_index_dtype(a):
    """Index dtype for gathers into `a`: int64 past the int32 offset
    range (requires x64 tracing so the dtype is not truncated)."""
    if a.size > _INT32_SAFE_ELEMS:
        return jnp.int64
    return jnp.int32


def _index_ctx(*operands):
    """Context for tracing an indexing op on `operands`: x64 when any
    operand is past the int32 offset range, so the WHOLE gather/scatter
    (including jnp-internal clipping and the autodiff transpose) keeps
    64-bit index arithmetic; a no-op otherwise."""
    import contextlib

    if any(op.size > _INT32_SAFE_ELEMS for op in operands):
        return jax.enable_x64()
    return contextlib.nullcontext()


def _as_gather_indices(a, indices):
    return indices.astype(_gather_index_dtype(a))


@register("take")
def take(a, indices, axis=0, mode="clip", **_):
    """Gather slices of ``a`` at ``indices`` along ``axis``; out-of-
    range handling per ``mode`` ("raise" clips — no device-side raise
    on XLA, matching the reference's accelerator behaviour)."""
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    with _index_ctx(a):
        return jnp.take(a, _as_gather_indices(a, indices), axis=int(axis),
                        mode=jmode)


@register("batch_take")
def batch_take(x, index, axis=-1, keepdims=False, mode="clip", **_):
    """Per-row element pick: ``index`` selects one entry along ``axis``
    for each leading position (take_along_axis with clipped indices)."""
    ax = int(axis) % x.ndim
    with _index_ctx(x):
        idx = jnp.clip(_as_gather_indices(x, index), 0, x.shape[ax] - 1)
        out = jnp.take_along_axis(x, jnp.expand_dims(idx, ax), axis=ax)
    if not keepdims:
        out = jnp.squeeze(out, axis=ax)
    return out


@register("one_hot")
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32", **_):
    """One-hot encode ``indices`` into a trailing ``depth`` axis, with
    ``on_value``/``off_value`` fills and output ``dtype``."""
    from ..base import np_dtype

    oh = jax.nn.one_hot(indices.astype(jnp.int32), int(depth))
    out = oh * (on_value - off_value) + off_value
    return out.astype(np_dtype(dtype))


@register("Embedding")
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False, **_):
    """reference: src/operator/tensor/indexing_op.cc Embedding — a gather
    feeding the MXU-friendly dense path; sparse_grad maps to the same dense
    gather on TPU (XLA scatter handles the grad)."""
    with _index_ctx(weight):
        return jnp.take(weight, _as_gather_indices(weight, data), axis=0)


@register("gather_nd")
def gather_nd(data, indices, **_):
    """N-D gather: ``indices`` is ``(M, ...)`` whose leading axis
    indexes the first M axes of ``data`` (reference gather_nd)."""
    with _index_ctx(data):
        return data[tuple(_as_gather_indices(data, indices))]


@register("scatter_nd")
def scatter_nd(data, indices, shape=(), **_):
    """Scatter ``data`` into zeros of ``shape`` at gather_nd-style
    ``indices``; duplicate indices overwrite (last write wins, the
    reference's nondeterminism pinned to XLA scatter order)."""
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    with _index_ctx(out):
        return out.at[tuple(_as_gather_indices(out, indices))].set(data)


@register("_backward_gather_nd", aliases=("gather_nd_accumulate",))
def gather_nd_accumulate(data, indices, shape=(), **_):
    """gather_nd's VJP: scatter-ADD ``data`` into zeros of ``shape`` so
    duplicate indices accumulate."""
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    with _index_ctx(out):
        return out.at[tuple(_as_gather_indices(out, indices))].add(data)


@register("where_nd", aliases=("boolean_mask_unsupported",))
def where_nd(cond, **_):
    """Unsupported-by-design stub: nonzero-style ops have
    data-dependent output shapes, which cannot stage under jit on
    TPU — raises with the static-capacity alternative."""
    raise NotImplementedError(
        "data-dependent output shapes are not jittable on TPU; "
        "use boolean_mask with static capacity"
    )


@register("index_copy")
def index_copy(old, index, new_tensor, **_):
    """Copy ``new_tensor`` rows into ``old`` at positions ``index``
    (out-of-place; the reference's contrib.index_copy)."""
    with _index_ctx(old):
        return old.at[_as_gather_indices(old, index)].set(new_tensor)


@register("index_add")
def index_add(old, index, new_tensor, **_):
    """Add ``new_tensor`` rows into ``old`` at positions ``index``;
    duplicate indices accumulate (contrib.index_add)."""
    with _index_ctx(old):
        return old.at[_as_gather_indices(old, index)].add(new_tensor)


# ---------------------------------------------------------------- linalg


@register("linalg_gemm")
def linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-3, **_):
    """BLAS-3 GEMM on the trailing two axes:
    ``alpha·op(a)·op(b) + beta·c`` (reference la_op.cc linalg_gemm)."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * c


@register("linalg_gemm2")
def linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0, **_):
    """GEMM without the additive term: ``alpha·op(a)·op(b)``."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def linalg_potrf(a, **_):
    """Cholesky factor L of a symmetric positive-definite ``a``
    (``a = L·Lᵀ``, lower triangular)."""
    return jnp.linalg.cholesky(a)


@register("linalg_potri")
def linalg_potri(a, **_):
    """Inverse of ``L·Lᵀ`` from a Cholesky factor ``a = L``:
    ``(L·Lᵀ)⁻¹ = L⁻ᵀ·L⁻¹`` (reference linalg_potri)."""
    l_inv = jnp.linalg.inv(a)
    return jnp.matmul(jnp.swapaxes(l_inv, -1, -2), l_inv)


@register("linalg_trmm")
def linalg_trmm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0, **_):
    """Triangular matmul: ``alpha·op(tri(a))·b`` (or ``b·op(tri(a))``
    with ``rightside``), ``lower`` picking the triangle of ``a``."""
    t = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        t = jnp.swapaxes(t, -1, -2)
    return alpha * (jnp.matmul(b, t) if rightside else jnp.matmul(t, b))


@register("linalg_trsm")
def linalg_trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0, **_):
    """Triangular solve: ``alpha · op(tri(a))⁻¹·b`` (or the
    ``rightside`` form ``b·op(tri(a))⁻¹``) via solve_triangular."""
    import jax.scipy.linalg as jsl

    t = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        t = jnp.swapaxes(t, -1, -2)
        lower = not lower
    if rightside:
        out = jsl.solve_triangular(jnp.swapaxes(t, -1, -2), jnp.swapaxes(b, -1, -2),
                                   lower=not lower)
        out = jnp.swapaxes(out, -1, -2)
    else:
        out = jsl.solve_triangular(t, b, lower=lower)
    return alpha * out


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(a, **_):
    """Sum of the log of the diagonal of the trailing 2-D block(s) —
    the log-determinant of a Cholesky factor."""
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_extractdiag")
def linalg_extractdiag(a, offset=0, **_):
    """The ``offset``-th diagonal of the trailing 2-D block(s) as a
    vector (batched jnp.diagonal)."""
    return jnp.diagonal(a, offset=int(offset), axis1=-2, axis2=-1)


@register("linalg_makediag")
def linalg_makediag(a, offset=0, **_):
    """Embed the trailing vector of ``a`` as the ``offset``-th diagonal
    of an otherwise-zero square matrix (inverse of extractdiag)."""
    n = a.shape[-1] + abs(int(offset))
    out = jnp.zeros(a.shape[:-1] + (n, n), dtype=a.dtype)
    i = jnp.arange(a.shape[-1])
    if offset >= 0:
        return out.at[..., i, i + offset].set(a)
    return out.at[..., i - offset, i].set(a)


@register("linalg_syrk")
def linalg_syrk(a, transpose=False, alpha=1.0, **_):
    """Symmetric rank-k update: ``alpha·a·aᵀ`` (``alpha·aᵀ·a`` with
    ``transpose``) on the trailing two axes."""
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("diag")
def diag(x, k=0, **_):
    """1-D input: build the matrix with ``x`` on diagonal ``k``;
    N-D input: extract diagonal ``k`` of the trailing 2-D block(s)
    (numpy diag/diagonal semantics)."""
    if x.ndim == 1:
        return jnp.diag(x, k=int(k))
    return jnp.diagonal(x, offset=int(k), axis1=-2, axis2=-1)


@register("trace_op", aliases=("trace",))
def trace(x, offset=0, axis1=0, axis2=1, **_):
    """Sum of the ``offset``-th diagonal over the ``(axis1, axis2)``
    plane (numpy trace semantics)."""
    return jnp.trace(x, offset=int(offset), axis1=int(axis1), axis2=int(axis2))


# ---------------------------------------------------------------- sequence ops


@register("SequenceMask", aliases=("sequence_mask",))
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0, **_):
    """Replace each sequence's positions past its ``sequence_length`` with
    ``value``; ``axis`` picks the (seq, batch) vs (batch, seq) layout,
    and without ``use_sequence_length`` the data passes through."""
    if not use_sequence_length or sequence_length is None:
        return data
    axis = int(axis)  # 0 = (seq, batch, ...), 1 = (batch, seq, ...)
    seq_axis, batch_axis = (0, 1) if axis == 0 else (1, 0)
    steps = jnp.arange(data.shape[seq_axis])
    shape = [1] * data.ndim
    shape[seq_axis] = data.shape[seq_axis]
    steps = steps.reshape(shape)
    lens_shape = [1] * data.ndim
    lens_shape[batch_axis] = data.shape[batch_axis]
    lens = sequence_length.reshape(lens_shape)
    return jnp.where(steps < lens, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceLast", aliases=("sequence_last",))
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0, **_):
    """Each sequence's LAST valid element — position
    ``sequence_length-1`` per batch entry (or the final step for all,
    without ``use_sequence_length``)."""
    axis = int(axis)
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length - 1).astype(jnp.int32)
    moved = jnp.moveaxis(data, axis, 0)  # (seq, batch, ...)
    return jax.vmap(lambda s, i: s[i], in_axes=(1, 0))(moved, idx)


@register("SequenceReverse", aliases=("sequence_reverse",))
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0, **_):
    """Reverse the first ``sequence_length`` steps of each (seq, batch)
    column, leaving the padding tail in place (whole-axis flip without
    ``use_sequence_length``)."""
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    seq_len = data.shape[0]
    steps = jnp.arange(seq_len)

    def rev_one(col, length):  # col: (seq, ...), length: scalar
        idx = jnp.where(steps < length, length - 1 - steps, steps)
        return col[idx]

    return jax.vmap(rev_one, in_axes=(1, 0), out_axes=1)(data, sequence_length.astype(jnp.int32))


@register("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip", **_):
    """Pick elements along an axis by per-position index
    (reference: src/operator/tensor/broadcast_reduce_op_index.cc pick)."""
    ax = int(axis) % data.ndim
    idx = index.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, data.shape[ax] - 1)
    else:  # wrap
        idx = idx % data.shape[ax]
    # index shape == data shape minus `axis` (broadcasting collapsed)
    idx_full = jnp.expand_dims(idx.reshape(
        tuple(d for i, d in enumerate(data.shape) if i != ax)), ax)
    out = jnp.take_along_axis(data, idx_full, axis=ax)
    if keepdims:
        return out
    return jnp.squeeze(out, axis=ax)


@register("choose_element_0index")
def choose_element_0index(lhs, rhs, **_):
    """Legacy 2-D row-wise pick: out[i] = lhs[i, rhs[i]] (reference:
    src/operator/tensor/broadcast_reduce_op_index.cc
    choose_element_0index, the deprecated alias of pick axis=1)."""
    idx = jnp.clip(rhs.astype(jnp.int32), 0, lhs.shape[1] - 1)
    return jnp.take_along_axis(lhs, idx[:, None], axis=1)[:, 0]


@register("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs, **_):
    """Legacy 2-D row-wise fill: out = lhs with out[i, rhs[i]] = mhs[i]
    (reference: fill_element_0index, the in-place companion of
    choose_element_0index)."""
    idx = jnp.clip(rhs.astype(jnp.int32), 0, lhs.shape[1] - 1)
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, idx].set(mhs.astype(lhs.dtype))


@register("SwapAxis", aliases=("swapaxes", "swapaxis"))
def swapaxes(data, dim1=0, dim2=0, **_):
    """reference: src/operator/swapaxis.cc"""
    return jnp.swapaxes(data, int(dim1), int(dim2))


@register("Crop")
def crop_like(data, *like, offset=(), h_w=(), center_crop=False, num_args=1, **_):
    """Crop data to the spatial size of a second input or explicit h_w
    (reference: src/operator/crop.cc)."""
    if like:
        th, tw = like[0].shape[2], like[0].shape[3]
    else:
        th, tw = h_w
    h, w = data.shape[2], data.shape[3]
    if center_crop:
        oh, ow = (h - th) // 2, (w - tw) // 2
    else:  # reference default: top-left at `offset` (crop-inl.h:130)
        oh, ow = offset if offset else (0, 0)
    return data[:, :, oh:oh + th, ow:ow + tw]


def encode_basic_index(ck):
    """Normalize a cleaned basic index into a hashable attr for
    _basic_index (slices become ('s', start, stop, step) tags)."""
    items = ck if isinstance(ck, tuple) else (ck,)
    out = []
    for it in items:
        if isinstance(it, builtins_slice):
            out.append(("s", it.start, it.stop, it.step))
        elif it is None:
            out.append(("n",))
        elif it is Ellipsis:
            out.append(("e",))
        else:
            out.append(("i", int(it)))
    return tuple(out)


def _decode_basic_index(key):
    out = []
    for it in key:
        if it[0] == "s":
            out.append(builtins_slice(it[1], it[2], it[3]))
        elif it[0] == "n":
            out.append(None)
        elif it[0] == "e":
            out.append(Ellipsis)
        else:
            out.append(it[1])
    return tuple(out)


@register("_basic_index")
def basic_index(x, key=(), **_):
    """Differentiable basic indexing: NDArray.__getitem__ routes here
    while autograd records, so slices/int-indexing join the tape (the
    reference's record-able Slice/At views, ndarray.cc Slice/At); the
    VJP is jax's own gather transpose (scatter into zeros)."""
    return x[_decode_basic_index(key)]
