"""Operator registry — TPU-native replacement for the nnvm op registry.

Reference: include/mxnet/op_attr_types.h (FCompute:263, FComputeEx:273),
nnvm ``NNVM_REGISTER_OP`` and the per-op attribute tables consumed by
``src/imperative/imperative.cc`` and ``src/executor/graph_executor.cc``.

Design (TPU-first): an operator here is a *pure jax function*
``fn(*tensor_inputs, **attrs) -> jax.Array | tuple``.  That single pure
function replaces the reference's whole per-op attribute bundle:

- shape/type inference  → ``jax.eval_shape`` on the same fn
- FCompute cpu/gpu      → XLA lowers the fn for any backend
- FGradient             → ``jax.vjp`` of the same fn
- kernel tuning/fusion  → XLA fusion (+ Pallas kernels where we override)

Eager dispatch jits each op keyed on (attrs, input avals) via
``jax.jit(..., static_argnames=...)`` so imperative NDArray calls hit a
compiled executable after the first call — this is the analog of the
reference engine's cached ThreadedOpr path (src/engine/threaded_engine.h).
"""

from __future__ import annotations

import functools
import os
import time

import jax
import numpy as _np

from .. import profiler as _prof
from .. import runtime_stats as _stats
from ..base import MXNetError

__all__ = ["Op", "register", "get", "list_ops", "apply_op",
           "compiled_cost", "cost_capture_active", "cost_snapshot",
           "install_bucket_hint", "bucket_hints", "clear_bucket_hints"]


_OP_REGISTRY: dict[str, "Op"] = {}

# Ordered tensor-input names per op (reference: each op's ListArguments()).
# Drives both nd.* kwarg handling and Symbol auto-created variables
# (e.g. FullyConnected with no weight= grows a "<name>_weight" variable,
# matching python/mxnet/symbol autogen behaviour).
OP_INPUT_NAMES = {
    "Convolution": ("data", "weight", "bias"),
    "Deconvolution": ("data", "weight", "bias"),
    "FullyConnected": ("data", "weight", "bias"),
    "BatchNorm": ("data", "gamma", "beta", "moving_mean", "moving_var"),
    "LayerNorm": ("data", "gamma", "beta"),
    "InstanceNorm": ("data", "gamma", "beta"),
    "L2Normalization": ("data",),
    "Embedding": ("data", "weight"),
    "LeakyReLU": ("data", "gamma"),
    "SoftmaxOutput": ("data", "label"),
    "choose_element_0index": ("lhs", "rhs"),
    "fill_element_0index": ("lhs", "mhs", "rhs"),
    "SVMOutput": ("data", "label"),
    "LinearRegressionOutput": ("data", "label"),
    "MAERegressionOutput": ("data", "label"),
    "LogisticRegressionOutput": ("data", "label"),
    "CTCLoss": ("data", "label", "data_lengths", "label_lengths"),
    "SequenceMask": ("data", "sequence_length"),
    "SequenceLast": ("data", "sequence_length"),
    "SequenceReverse": ("data", "sequence_length"),
    "dot": ("lhs", "rhs"),
    "batch_dot": ("lhs", "rhs"),
    "where": ("condition", "x", "y"),
    "take": ("a", "indices"),
    "ROIPooling": ("data", "rois"),
    "BilinearSampler": ("data", "grid"),
    "GridGenerator": ("data",),
    "SpatialTransformer": ("data", "loc"),
    "RNN": ("data", "parameters", "state", "state_cell"),
}

# Inputs that are auxiliary states (not gradient targets; updated by the
# executor, reference: symbol list_auxiliary_states / NDArray aux states)
OP_AUX_INPUTS = {
    "BatchNorm": ("moving_mean", "moving_var"),
}

# ops whose label-ish inputs get auto-created as "<name>_label" variables
OP_LABEL_INPUTS = {"SoftmaxOutput", "SVMOutput", "LinearRegressionOutput",
                   "MAERegressionOutput", "LogisticRegressionOutput", "CTCLoss"}


def _hashable(v):
    """Normalize attr values to hashable, canonical forms."""
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, _np.ndarray):
        # host numpy by the isinstance guard — never a device value
        return tuple(v.ravel().tolist()) if v.size < 64 else v.tobytes()  # mxlint: disable=trace-host-sync
    if isinstance(v, _np.generic):
        return v.item()  # mxlint: disable=trace-host-sync -- np scalar, host-side
    return v


# Pad-to-bucket hints: {op name: {attr: ladder tuple or None}}.  A
# hinted integer attr is rounded UP onto its ladder during attr
# canonicalization, so a per-call churning dimension (sequence length,
# pad amount) collapses onto O(log) jit-cache keys instead of one
# executable per distinct value — the registry-level actuator the
# autopilot's recompile-storm reflex installs (the op must tolerate the
# larger value as padding; that is what makes the attr a *dimension*).
# Empty by default: the hot path pays one falsy-dict check.
_BUCKET_HINTS: dict = {}


def install_bucket_hint(op_name, attr, ladder=None):
    """Round ``attr`` of ``op_name`` up onto ``ladder`` (a sorted tuple
    of ints; values past the top rung round up to a multiple of it) at
    every future :meth:`Op.canonicalize_attrs`.  ``ladder=None`` means
    next power of two.  Idempotent per (op, attr); returns the
    installed ladder."""
    if ladder is not None:
        ladder = tuple(sorted(int(v) for v in ladder))
        if not ladder or any(v <= 0 for v in ladder):
            raise MXNetError("bucket ladder must be positive ints, got "
                             "%r" % (ladder,))
    _BUCKET_HINTS.setdefault(str(op_name), {})[str(attr)] = ladder
    return ladder


def bucket_hints():
    """{op: {attr: ladder}} of every installed hint (a copy)."""
    return {op: dict(hints) for op, hints in _BUCKET_HINTS.items()}


def clear_bucket_hints():
    """Drop every installed hint (tests / manual rollback)."""
    _BUCKET_HINTS.clear()


def _bucket_up(v, ladder):
    """Smallest rung >= v; past the top rung, the next multiple of it.
    ``ladder=None`` -> next power of two (>= 1)."""
    if ladder is None:
        b = 1
        while b < v:
            b *= 2
        return b
    for rung in ladder:
        if rung >= v:
            return rung
    top = ladder[-1]
    return ((v + top - 1) // top) * top


class Op:
    """A registered operator.

    Attributes
    ----------
    name : canonical op name (reference-compatible, e.g. 'Convolution').
    fn : pure function ``fn(*arrays, **attrs)``.
    num_outputs : static output count, or a callable(attrs)->int.
    """

    def __init__(self, name, fn, num_outputs=1, aliases=(), defaults=None,
                 traced_attrs=()):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.aliases = tuple(aliases)
        self.defaults = dict(defaults or {})
        # attrs traced as jit ARGUMENTS instead of baked into the cache
        # key: a value that varies per call (scheduler lr, bias-correction
        # t, eager `x * python_scalar`) must not trigger a recompile per
        # step.  Only safe for attrs the op fn uses purely in math — an
        # attr the fn branches on in Python must stay static.
        self.traced_attrs = frozenset(traced_attrs)
        self._jit_cache = {}
        # cache key -> normalized XLA cost/memory analysis of that
        # entry (or None when the backend exposes none) — captured at
        # compile time by analyze_entry(), read by cost_snapshot()
        self._cost = {}

    def __repr__(self):
        return "Op(%s)" % self.name

    def canonicalize_attrs(self, attrs):
        out = dict(self.defaults)
        out.update(attrs)
        out = {k: _hashable(v) for k, v in out.items()}
        if _BUCKET_HINTS:
            hints = _BUCKET_HINTS.get(self.name)
            if hints:
                for attr, ladder in hints.items():
                    v = out.get(attr)
                    if isinstance(v, int) and not isinstance(v, bool):
                        b = _bucket_up(v, ladder)
                        if b != v:
                            out[attr] = b
                            _stats.inc("bucket_hint_rounded")
        return out

    def bind_attrs(self, attrs):
        """A pure fn of tensors only, with attrs closed over (for vjp/trace)."""
        fn = self.fn
        return functools.partial(fn, **attrs)

    def jitted(self, attrs):
        """Compiled entry point for eager dispatch, cached per attr-set.

        Attrs named in ``traced_attrs`` (when numeric) are fed to the
        compiled fn as weak-typed scalar arguments — the cache key holds
        only their *names*, so a changing value reuses the executable."""
        return self.jitted_ex(attrs)[0]

    def _split_attrs(self, attrs):
        """``(cache key, traced names, static attrs, traced attrs)`` for
        an attr-set — the single definition of the jit-cache key, shared
        by :meth:`jitted_ex` and :meth:`analyze_entry`.

        A ``jax.core.Tracer`` value for a traced-attr name also routes to
        the traced side: when a whole-step program (compiled_step.py)
        traces an optimizer update, the per-step scalars arrive as
        tracers and must become jit arguments, never cache-key
        components (tracers are unhashable by design)."""
        traced = {k: v for k, v in attrs.items()
                  if k in self.traced_attrs
                  and ((isinstance(v, (int, float))
                        and not isinstance(v, bool))
                       or isinstance(v, jax.core.Tracer))}
        if not traced:
            return tuple(sorted(attrs.items())), (), attrs, traced
        static = {k: v for k, v in attrs.items() if k not in traced}
        tnames = tuple(sorted(traced))
        return (tuple(sorted(static.items())), tnames), tnames, static, \
            traced

    def jitted_ex(self, attrs):
        """:meth:`jitted` plus the jit-cache hit flag.

        The dispatch layer uses the miss flag to attribute compile
        wall-time (runtime_stats counters, profiler miss spans); every
        miss also registers its cache key with the recompile-storm
        detector.  The telemetry cost on the hit path is one dict
        lookup and two integer increments."""
        key, tnames, static, traced = self._split_attrs(attrs)
        if not tnames:
            entry = self._jit_cache.get(key)
            hit = entry is not None
            if not hit:
                entry = jax.jit(self.bind_attrs(attrs))
                self._jit_cache[key] = entry
                _stats.record_compile_key(self.name, key)
            _stats.record_dispatch(self.name, "hit" if hit else "miss")
            return entry, hit
        entry = self._jit_cache.get(key)
        hit = entry is not None
        if not hit:
            fn = self.fn

            def call(arrays, tvals):
                kw = dict(static)
                kw.update(zip(tnames, tvals))
                return fn(*arrays, **kw)

            entry = jax.jit(call)
            self._jit_cache[key] = entry
            _stats.record_compile_key(self.name, key)
        _stats.record_dispatch(self.name, "hit" if hit else "miss")
        # python floats stay weak-typed under tracing: no recompile across
        # values AND no dtype promotion of bf16/fp16 tensors; a tracer
        # (an enclosing whole-step trace feeding per-step scalars) is
        # already abstract and passes through as-is
        tvals = tuple(traced[k] if isinstance(traced[k], jax.core.Tracer)
                      else float(traced[k]) for k in tnames)
        return functools.partial(_call_traced, entry, tvals), hit

    def analyze_entry(self, attrs, arrays):
        """Capture XLA ``cost_analysis()``/``memory_analysis()`` for the
        cache entry keyed by ``attrs`` and store it on the entry (in
        ``self._cost``), once per entry.

        Compile-time only: the dispatch layer calls this on jit-cache
        misses, never on the hit path, and it no-ops unless cost capture
        is active (:func:`cost_capture_active`).  The AOT
        ``lower().compile()`` pays one extra XLA compile for the entry's
        first aval — a bounded, compile-path-only cost, surfaced in the
        ``cost_analysis_seconds`` counter.  Any backend that lacks the
        analyses just yields an empty record (try/except)."""
        if not cost_capture_active():
            return None
        key, tnames, _static, traced = self._split_attrs(attrs)
        if key in self._cost:
            return self._cost[key]
        entry = self._jit_cache.get(key)
        if entry is None:
            return None
        t0 = time.perf_counter()
        try:
            # lower on avals, not the live arrays: shape/dtype is all
            # the analysis needs, and concrete cross-device inputs
            # (the kvstore-reduce fallback path) would fail pjit's
            # device check here even though the call itself succeeded
            # on gathered copies
            specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                     if isinstance(a, jax.Array) else a for a in arrays]
            if tnames:
                tvals = tuple(float(traced[k]) for k in tnames)
                compiled = entry.lower(tuple(specs), tvals).compile()
            else:
                compiled = entry.lower(*specs).compile()
            cost = compiled_cost(compiled)
        except Exception:  # analysis must never break dispatch
            cost = None
        self._cost[key] = cost
        # entries counts SUCCESSFUL analyses (agrees with the per-op
        # "analyzed" in cost_snapshot); failed attempts get their own
        # counter, and both accrue their wall-time
        _stats.inc("cost_analysis_entries" if cost
                   else "cost_analysis_failures")
        _stats.inc("cost_analysis_seconds", time.perf_counter() - t0)
        return cost

    def nout(self, attrs):
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs


def _call_traced(entry, tvals, *arrays):
    return entry(arrays, tvals)


# ------------------------------------------------------ cost analytics


def cost_capture_active():
    """Whether jit-cache misses should capture XLA cost analytics.

    Capture pays one extra AOT compile per cache entry, so it runs only
    when telemetry wants the data: the profiler is recording, a
    ``MXNET_TPU_DIAG`` dump destination is set, or
    ``MXNET_TPU_COST_ANALYSIS=1`` forces it; ``=0`` disables it
    unconditionally.  Checked only on the (already compile-bound) miss
    path — the hit path never reaches it — so the env reads are live,
    not import-time snapshots (both vars toggle at runtime)."""
    force = os.environ.get("MXNET_TPU_COST_ANALYSIS", "")
    if force == "0":
        return False
    if force == "1" or os.environ.get("MXNET_TPU_DIAG"):
        return True
    return _prof._state["running"]


def compiled_cost(compiled):
    """Normalize an XLA ``Compiled``'s analyses into one flat dict:
    ``flops`` / ``bytes_accessed`` (cost model, per call) and
    ``output_bytes`` / ``temp_bytes`` / ``argument_bytes`` /
    ``generated_code_bytes`` (memory analysis, per executable).
    Backends differ in what they expose; absent pieces are simply
    missing keys, and a fully silent backend yields ``None``."""
    out = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # CPU returns [dict]
            ca = ca[0] if ca else {}
        for src, dst in (("flops", "flops"),
                         ("bytes accessed", "bytes_accessed")):
            v = ca.get(src)
            if v is not None and v >= 0:
                out[dst] = float(v)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for src, dst in (
                ("output_size_in_bytes", "output_bytes"),
                ("temp_size_in_bytes", "temp_bytes"),
                ("argument_size_in_bytes", "argument_bytes"),
                ("generated_code_size_in_bytes", "generated_code_bytes")):
            v = getattr(ma, src, None)
            if v is not None:
                out[dst] = int(v)
    except Exception:
        pass
    return out or None


def cost_snapshot():
    """Read-side aggregate over every registered op's jit cache:
    ``{op: {"cache_entries", "analyzed", "flops_per_call",
    "bytes_per_call", "output_bytes", "temp_bytes",
    "argument_bytes"}}``.

    ``*_per_call`` are means over the analyzed entries (cost-model,
    per executed call); the ``*_bytes`` footprints are sums over
    entries (what the cache as a whole holds in output/temp buffers).
    Iterates the registry — read path only, never dispatch."""
    out = {}
    seen = set()
    # list() copies: concurrent dispatch may register entries/analyses
    # while a snapshot (e.g. the SIGUSR1 diag handler) iterates
    for op in list(_OP_REGISTRY.values()):
        if id(op) in seen:
            continue
        seen.add(id(op))
        n = len(op._jit_cache)
        analyzed = [c for c in list(op._cost.values()) if c]
        if not n and not analyzed:
            continue
        rec = {"cache_entries": n, "analyzed": len(analyzed)}
        for k, dst in (("flops", "flops_per_call"),
                       ("bytes_accessed", "bytes_per_call")):
            vals = [c[k] for c in analyzed if k in c]
            if vals:
                rec[dst] = sum(vals) / len(vals)
        for k in ("output_bytes", "temp_bytes", "argument_bytes"):
            vals = [c[k] for c in analyzed if k in c]
            if vals:
                rec[k] = int(sum(vals))
        out[op.name] = rec
    return out


def register(name, num_outputs=1, aliases=(), traced_attrs=(), **defaults):
    """Decorator: register a pure jax function as an operator.

    ``@register("dot", aliases=["Dot"])``
    """

    def deco(fn):
        op = Op(name, fn, num_outputs=num_outputs, aliases=aliases,
                defaults=defaults, traced_attrs=traced_attrs)
        for n in (name,) + op.aliases:
            prev = _OP_REGISTRY.get(n)
            if prev is not None and prev.fn is not fn:
                raise MXNetError(
                    "Operator name %r is already registered (to %r); use "
                    "alias() to share an implementation explicitly" % (n, prev.name))
            _OP_REGISTRY[n] = op
        return fn

    return deco


def get(name):
    op = _OP_REGISTRY.get(name)
    if op is None:
        raise MXNetError("Operator %r is not registered" % (name,))
    return op


def alias(name, target):
    """Register `name` as another name for the existing op `target`.

    Raises when `target` is not registered, when `name` is already bound
    to a different op, or when the two names carry conflicting
    tensor-input arities in ``OP_INPUT_NAMES`` (a mismatched alias would
    silently mis-bind positional inputs)."""
    op = _OP_REGISTRY.get(target)
    if op is None:
        raise MXNetError(
            "alias(%r, %r): target operator is not registered" % (name, target))
    prev = _OP_REGISTRY.get(name)
    if prev is not None:
        if prev is op:
            return
        raise MXNetError(
            "alias(%r, %r): name is already registered (to %r)"
            % (name, target, prev.name))
    n_in, t_in = OP_INPUT_NAMES.get(name), OP_INPUT_NAMES.get(op.name)
    if n_in is not None and t_in is not None and len(n_in) != len(t_in):
        raise MXNetError(
            "alias(%r, %r): tensor-input arity mismatch (%d vs %d)"
            % (name, target, len(n_in), len(t_in)))
    _OP_REGISTRY[name] = op


def list_ops():
    return sorted(set(o.name for o in _OP_REGISTRY.values()))


def apply_op(name, *arrays, **attrs):
    """Eagerly apply a registered op to raw jax arrays."""
    op = get(name)
    attrs = op.canonicalize_attrs(attrs)
    counted = False
    try:
        entry, hit = op.jitted_ex(attrs)  # counts the call (hit/miss)
        counted = True
        if hit and not _prof._state["running"] \
                and not _stats.DIAG_TIMING:  # guard-first fast path
            return entry(*arrays)
        t0 = _prof._now_us()
        result = entry(*arrays)
        dur = _prof._now_us() - t0
        if not hit:
            _stats.add_compile_seconds(op.name, dur / 1e6)
            op.analyze_entry(attrs, arrays)
        else:
            # cache-warm only: miss dur is compile-dominated and lives
            # in compile_seconds (see _dispatch_jit in ndarray.py)
            _stats.add_dispatch_seconds(op.name, dur / 1e6)
        if _prof._state["running"]:
            # event allocation only while recording — a DIAG-timing run
            # with the profiler off must not build dicts per call
            ev_args = {"op": op.name, "cache": "hit" if hit else "miss"}
            if not hit:
                ev_args["compile_ms"] = round(dur / 1e3, 3)
            _prof.add_event("dispatch:" + op.name, "operator", "X",
                            ts=t0, dur=dur, args=ev_args)
        return result
    except TypeError:
        # attrs that fail jit staging (e.g. unhashable leftovers) fall back
        # to op-by-op eager tracing.  An unhashable cache key raises out
        # of jitted_ex before the call is counted — count it here so
        # calls >= fallbacks always holds in snapshot()
        if not counted:
            _stats.record_dispatch(op.name, "uncached")
        _stats.record_fallback(op.name, "eager-trace")
        return op.bind_attrs(attrs)(*arrays)
