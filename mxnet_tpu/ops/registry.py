"""Operator registry — TPU-native replacement for the nnvm op registry.

Reference: include/mxnet/op_attr_types.h (FCompute:263, FComputeEx:273),
nnvm ``NNVM_REGISTER_OP`` and the per-op attribute tables consumed by
``src/imperative/imperative.cc`` and ``src/executor/graph_executor.cc``.

Design (TPU-first): an operator here is a *pure jax function*
``fn(*tensor_inputs, **attrs) -> jax.Array | tuple``.  That single pure
function replaces the reference's whole per-op attribute bundle:

- shape/type inference  → ``jax.eval_shape`` on the same fn
- FCompute cpu/gpu      → XLA lowers the fn for any backend
- FGradient             → ``jax.vjp`` of the same fn
- kernel tuning/fusion  → XLA fusion (+ Pallas kernels where we override)

Eager dispatch jits each op keyed on (attrs, input avals) via
``jax.jit(..., static_argnames=...)`` so imperative NDArray calls hit a
compiled executable after the first call — this is the analog of the
reference engine's cached ThreadedOpr path (src/engine/threaded_engine.h).
"""

from __future__ import annotations

import functools

import jax
import numpy as _np

from ..base import MXNetError

__all__ = ["Op", "register", "get", "list_ops", "apply_op"]

_OP_REGISTRY: dict[str, "Op"] = {}

# Ordered tensor-input names per op (reference: each op's ListArguments()).
# Drives both nd.* kwarg handling and Symbol auto-created variables
# (e.g. FullyConnected with no weight= grows a "<name>_weight" variable,
# matching python/mxnet/symbol autogen behaviour).
OP_INPUT_NAMES = {
    "Convolution": ("data", "weight", "bias"),
    "Deconvolution": ("data", "weight", "bias"),
    "FullyConnected": ("data", "weight", "bias"),
    "BatchNorm": ("data", "gamma", "beta", "moving_mean", "moving_var"),
    "LayerNorm": ("data", "gamma", "beta"),
    "InstanceNorm": ("data", "gamma", "beta"),
    "L2Normalization": ("data",),
    "Embedding": ("data", "weight"),
    "LeakyReLU": ("data", "gamma"),
    "SoftmaxOutput": ("data", "label"),
    "LinearRegressionOutput": ("data", "label"),
    "MAERegressionOutput": ("data", "label"),
    "LogisticRegressionOutput": ("data", "label"),
    "CTCLoss": ("data", "label", "data_lengths", "label_lengths"),
    "SequenceMask": ("data", "sequence_length"),
    "SequenceLast": ("data", "sequence_length"),
    "SequenceReverse": ("data", "sequence_length"),
    "dot": ("lhs", "rhs"),
    "batch_dot": ("lhs", "rhs"),
    "where": ("condition", "x", "y"),
    "take": ("a", "indices"),
    "ROIPooling": ("data", "rois"),
    "BilinearSampler": ("data", "grid"),
    "GridGenerator": ("data",),
    "SpatialTransformer": ("data", "loc"),
    "RNN": ("data", "parameters", "state", "state_cell"),
}

# Inputs that are auxiliary states (not gradient targets; updated by the
# executor, reference: symbol list_auxiliary_states / NDArray aux states)
OP_AUX_INPUTS = {
    "BatchNorm": ("moving_mean", "moving_var"),
}

# ops whose label-ish inputs get auto-created as "<name>_label" variables
OP_LABEL_INPUTS = {"SoftmaxOutput", "LinearRegressionOutput",
                   "MAERegressionOutput", "LogisticRegressionOutput", "CTCLoss"}


def _hashable(v):
    """Normalize attr values to hashable, canonical forms."""
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, _np.ndarray):
        return tuple(v.ravel().tolist()) if v.size < 64 else v.tobytes()
    if isinstance(v, _np.generic):
        return v.item()
    return v


class Op:
    """A registered operator.

    Attributes
    ----------
    name : canonical op name (reference-compatible, e.g. 'Convolution').
    fn : pure function ``fn(*arrays, **attrs)``.
    num_outputs : static output count, or a callable(attrs)->int.
    """

    def __init__(self, name, fn, num_outputs=1, aliases=(), defaults=None):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.aliases = tuple(aliases)
        self.defaults = dict(defaults or {})
        self._jit_cache = {}

    def __repr__(self):
        return "Op(%s)" % self.name

    def canonicalize_attrs(self, attrs):
        out = dict(self.defaults)
        out.update(attrs)
        return {k: _hashable(v) for k, v in out.items()}

    def bind_attrs(self, attrs):
        """A pure fn of tensors only, with attrs closed over (for vjp/trace)."""
        fn = self.fn
        return functools.partial(fn, **attrs)

    def jitted(self, attrs):
        """Compiled entry point for eager dispatch, cached per attr-set."""
        key = tuple(sorted(attrs.items()))
        entry = self._jit_cache.get(key)
        if entry is None:
            entry = jax.jit(self.bind_attrs(attrs))
            self._jit_cache[key] = entry
        return entry

    def nout(self, attrs):
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs


def register(name, num_outputs=1, aliases=(), **defaults):
    """Decorator: register a pure jax function as an operator.

    ``@register("dot", aliases=["Dot"])``
    """

    def deco(fn):
        op = Op(name, fn, num_outputs=num_outputs, aliases=aliases, defaults=defaults)
        _OP_REGISTRY[name] = op
        for a in aliases:
            _OP_REGISTRY[a] = op
        return fn

    return deco


def get(name):
    op = _OP_REGISTRY.get(name)
    if op is None:
        raise MXNetError("Operator %r is not registered" % (name,))
    return op


def alias(name, target):
    """Register `name` as another name for an existing op (no-op if taken
    or if `target` is absent).  Use only when the tensor-input arity
    matches — a mismatched alias silently mis-binds positional inputs."""
    op = _OP_REGISTRY.get(target)
    if op is not None:
        _OP_REGISTRY.setdefault(name, op)


def list_ops():
    return sorted(set(o.name for o in _OP_REGISTRY.values()))


def apply_op(name, *arrays, **attrs):
    """Eagerly apply a registered op to raw jax arrays."""
    op = get(name)
    attrs = op.canonicalize_attrs(attrs)
    try:
        return op.jitted(attrs)(*arrays)
    except TypeError:
        # attrs that fail jit staging (e.g. unhashable leftovers) fall back
        # to op-by-op eager tracing
        return op.bind_attrs(attrs)(*arrays)
