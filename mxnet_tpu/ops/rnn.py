"""Monolithic multi-layer RNN op (vanilla RNN / LSTM / GRU).

Reference: src/operator/rnn.cc:47 (rnn_enum rnn-inl.h:49), the cuDNN
path src/operator/cudnn_rnn-inl.h and CPU impl src/operator/rnn_impl.h.

TPU-native design: time recurrence is a single ``lax.scan`` per
layer/direction — XLA compiles the whole stack into one fused loop with
the gate matmuls on the MXU (batched (B,in)x(in,4H)).  Parameter
layout matches the reference's packed cuDNN format: per layer, per
direction: W_i2h, W_h2h (flattened, gates-major), then all biases
b_i2h, b_h2h — so checkpoints round-trip with the reference layout.
Gate orders follow cuDNN: LSTM = (i, f, g, o), GRU = (r, z, n).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total packed parameter count (reference: rnn-inl.h GetRnnParamSize)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * dirs
        size += dirs * gates * state_size * (in_size + state_size + 2)
    return size


def _unpack(parameters, num_layers, input_size, state_size, dirs, gates):
    """Slice the packed parameter vector into per-(layer,dir) weights."""
    ws, off = [], 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * dirs
        layer_ws = []
        for d in range(dirs):
            n_i2h = gates * state_size * in_size
            n_h2h = gates * state_size * state_size
            w_i2h = parameters[off:off + n_i2h].reshape(
                (gates * state_size, in_size))
            off += n_i2h
            w_h2h = parameters[off:off + n_h2h].reshape(
                (gates * state_size, state_size))
            off += n_h2h
            layer_ws.append([w_i2h, w_h2h, None, None])
        ws.append(layer_ws)
    for layer in range(num_layers):
        for d in range(dirs):
            n_b = gates * state_size
            ws[layer][d][2] = parameters[off:off + n_b]
            off += n_b
            ws[layer][d][3] = parameters[off:off + n_b]
            off += n_b
    return ws


def _cell_step(mode, state_size, clip_min=None, clip_max=None):
    if mode == "lstm":
        def step(carry, gates_x, w_h2h, b_h2h):
            h, c = carry
            g = gates_x + h @ w_h2h.T + b_h2h
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            c_new = f * c + i * jnp.tanh(gg)
            if clip_min is not None:
                # clip every step (reference: cudnn_rnn clip mode), not
                # just the final state
                c_new = jnp.clip(c_new, clip_min, clip_max)
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
    elif mode == "gru":
        def step(carry, gates_x, w_h2h, b_h2h):
            (h,) = carry
            gh = h @ w_h2h.T + b_h2h
            xr, xz, xn = jnp.split(gates_x, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1.0 - z) * n + z * h
            return (h_new,), h_new
    else:
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

        def step(carry, gates_x, w_h2h, b_h2h):
            (h,) = carry
            h_new = act(gates_x + h @ w_h2h.T + b_h2h)
            return (h_new,), h_new
    return step


def _run_direction(x, h0, c0, w_i2h, w_h2h, b_i2h, b_h2h, mode, state_size,
                   reverse, clip_min=None, clip_max=None):
    """One layer, one direction: scan over time.  x: (T, B, in)."""
    # hoist the input projection out of the loop: one big MXU matmul
    gates_x = jnp.einsum("tbi,gi->tbg", x, w_i2h) + b_i2h
    step = _cell_step(mode, state_size, clip_min, clip_max)
    carry0 = (h0, c0) if mode == "lstm" else (h0,)

    def body(carry, gx):
        return step(carry, gx, w_h2h, b_h2h)

    carry, ys = lax.scan(body, carry0, gates_x, reverse=reverse)
    return carry, ys


@register("RNN")
def rnn(key, data, parameters, state, state_cell=None, state_size=0,
        num_layers=1, bidirectional=False, mode="lstm", p=0.0,
        state_outputs=False, projection_size=None, use_sequence_length=False,
        sequence_length=None, lstm_state_clip_min=None,
        lstm_state_clip_max=None, lstm_state_clip_nan=False, **_):
    """data: (seq, batch, input); state: (L*dirs, batch, H).

    Returns output (T,B,H*dirs), or (output, state_out[, statecell_out])
    with ``state_outputs``.
    """
    state_size = int(state_size)
    num_layers = int(num_layers)
    dirs = 2 if bidirectional else 1
    gates = _GATES[mode]
    input_size = data.shape[2]
    ws = _unpack(parameters, num_layers, input_size, state_size, dirs, gates)

    x = data
    h_outs, c_outs = [], []
    for layer in range(num_layers):
        if layer > 0 and p > 0.0 and key is not None:
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
        dir_outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            # a batch-1 begin state broadcasts to the data batch (the
            # symbolic cells' concrete stand-in for the reference's
            # deferred batch dim; scan carries need the full shape)
            bcast = (data.shape[1], state_size)
            h0 = jnp.broadcast_to(state[idx], bcast)
            c0 = jnp.broadcast_to(state_cell[idx], bcast) \
                if mode == "lstm" else None
            w_i2h, w_h2h, b_i2h, b_h2h = ws[layer][d]
            carry, ys = _run_direction(
                x, h0, c0, w_i2h, w_h2h, b_i2h, b_h2h, mode, state_size,
                reverse=(d == 1), clip_min=lstm_state_clip_min,
                clip_max=lstm_state_clip_max)
            if mode == "lstm":
                hT, cT = carry
                c_outs.append(cT)
            else:
                (hT,) = carry
            h_outs.append(hT)
            dir_outs.append(ys)
        x = jnp.concatenate(dir_outs, axis=-1) if dirs == 2 else dir_outs[0]

    out = x
    if not state_outputs:
        return out
    h_state = jnp.stack(h_outs, axis=0)
    if mode == "lstm":
        return out, h_state, jnp.stack(c_outs, axis=0)
    return out, h_state


def _rnn_nout(attrs):
    if not attrs.get("state_outputs"):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


# re-register with dynamic output count
from .registry import _OP_REGISTRY  # noqa: E402

_OP_REGISTRY["RNN"].num_outputs = _rnn_nout
