"""mx.image — image IO, resize/crop helpers, augmenters, ImageIter.

Reference: python/mxnet/image/image.py (imdecode/imresize/crops,
Augmenter classes, CreateAugmenter, ImageIter) over the C++ pipeline
src/io/image_aug_default.cc.

TPU-native notes: per-sample decode/augment stays on host (cv2/PIL +
numpy — these release the GIL inside DataLoader threads); the batched
tensor is transferred to HBM once.  That is exactly the reference's
split (OpenCV on CPU workers → device copy in the executor).
"""

from __future__ import annotations

import os
import threading

import numpy as _np

from . import io as _io
from . import ndarray, recordio
from .base import MXNetError

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "random_size_crop", "color_normalize",
           "Augmenter", "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "CenterCropAug", "HorizontalFlipAug", "CastAug",
           "ColorNormalizeAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "HueJitterAug", "LightingAug",
           "ColorJitterAug", "RandomOrderAug", "RandomGrayAug",
           "SequentialAug", "CreateAugmenter", "ImageIter"]


def _cv2():
    try:
        import cv2

        return cv2
    except ImportError:
        return None


class _HostArray(_np.ndarray):
    """numpy view that also answers the NDArray read surface augmenters
    use (`asnumpy`), so user augmenters written against the documented
    NDArray contract keep working on the host-numpy fast path."""

    def asnumpy(self):
        return _np.asarray(self)


def _to_host(src):
    """NDArray|numpy -> numpy view on host.  The whole augmentation
    chain runs on host numpy (one HBM transfer per *batch*, not per
    sample/op — a per-op device round-trip costs ~15-20 ms through a
    TPU relay and a fresh XLA compile per crop shape)."""
    return src.asnumpy() if isinstance(src, ndarray.NDArray) else src


def _like(out, ref):
    """Wrap a host array to match the caller's container type, so the
    public augmenter API stays NDArray->NDArray (reference behavior)
    while iterators feed host arrays through the same objects."""
    if isinstance(ref, ndarray.NDArray):
        return ndarray.array(out)
    return out.view(_HostArray) if isinstance(out, _np.ndarray) else out


def _imdecode_np(buf, flag=1, to_rgb=True):
    """Decode an image byte buffer to a host HWC uint8 numpy array."""
    if bytes(buf[:4]) == b"IMG0":
        # records written by earlier versions of this framework carried a
        # format tag before the encoded bytes; no real image format
        # starts with IMG0, so stripping it is unambiguous
        buf = buf[4:]
    cv2 = _cv2()
    if cv2 is not None:
        arr = cv2.imdecode(_np.frombuffer(buf, dtype=_np.uint8),
                           cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
        if arr is None:
            raise MXNetError("imdecode failed")
        if flag and to_rgb:
            arr = arr[:, :, ::-1]
        if not flag:
            arr = arr[:, :, None]
    else:
        import io as _pyio

        from PIL import Image

        img = Image.open(_pyio.BytesIO(buf))
        img = img.convert("RGB" if flag else "L")
        arr = _np.asarray(img)
        if not flag:
            arr = arr[:, :, None]
        elif not to_rgb:
            arr = arr[:, :, ::-1]
    return _np.ascontiguousarray(arr)


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to an NDArray HWC(BGR→RGB)
    (reference: image.py imdecode over cv::imdecode)."""
    return ndarray.array(_imdecode_np(buf, flag=flag, to_rgb=to_rgb),
                         dtype="uint8")


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    """Resize HWC image (reference: image.py imresize).  Type-preserving:
    numpy in -> numpy out, NDArray in -> NDArray out."""
    arr = _to_host(src)
    cv2 = _cv2()
    if cv2 is not None:
        out = cv2.resize(arr, (int(w), int(h)),
                         interpolation=_cv2_interp(interp))
        if out.ndim == 2:
            out = out[:, :, None]
    else:
        from .gluon.data.vision.transforms import _resize_np

        out = _resize_np(arr, (int(w), int(h)))
    return _like(out.astype(arr.dtype, copy=False), src)


def _cv2_interp(interp):
    import cv2

    return {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR, 2: cv2.INTER_CUBIC,
            3: cv2.INTER_AREA, 4: cv2.INTER_LANCZOS4}.get(int(interp),
                                                          cv2.INTER_LINEAR)


def resize_short(src, size, interp=2):
    """Resize so the shorter side equals `size`, keeping aspect
    (reference: image.py resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    # crop on host: NDArray slicing would trace one XLA program per
    # distinct crop shape
    out = _like(_to_host(src)[y0:y0 + h, x0:x0 + w], src)
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h)
    return out, (x0, y0, new_w, new_h)


class _SampleScopedStream:
    """RNG facade for augmenter draws over any random-module-like
    fallback (np.random here; the Python `random` module for the det
    augmenters in image_detection.py).

    By default every attribute resolves to the fallback's global
    stream, so single-threaded augmentation reproduces under
    np.random.seed/random.seed exactly as before.  A preprocess worker
    thread installs a per-sample generator (seeded by a draw the
    CALLING thread made from the global stream), so
    preprocess_threads>1 keeps sample contents reproducible no matter
    which pool thread runs which sample — the property the reference
    gets from per-worker seeded RNGs
    (src/io/iter_image_recordio_2.cc kRandMagic).  ADVICE r4 #3.
    """

    def __init__(self, fallback):
        self._fallback = fallback
        self._local = threading.local()

    def set_sample_rng(self, rng):
        self._local.rng = rng

    def __getattr__(self, name):
        rng = getattr(self._local, "rng", None)
        return getattr(self._fallback if rng is None else rng, name)


_nprand = _SampleScopedStream(_np.random)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _nprand.randint(0, w - new_w + 1)
    y0 = _nprand.randint(0, h - new_h + 1)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _nprand.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_nprand.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * aspect)))
        new_h = int(round(_np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = _nprand.randint(0, w - new_w + 1)
            y0 = _nprand.randint(0, h - new_h + 1)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    """(src - mean) / std; either stat may be None (reference:
    image.py color_normalize tolerates std-only / mean-only)."""
    arr = _to_host(src).astype(_np.float32)
    if mean is not None:
        arr = arr - _np.asarray(mean, dtype=_np.float32)
    if std is not None:
        arr = arr / _np.asarray(std, dtype=_np.float32)
    return _like(arr, src)


# ------------------------------------------------------------- augmenters


class Augmenter:
    """Image augmenter base (reference: image.py Augmenter)."""

    def __init__(self, **kwargs):
        # array-valued kwargs (mean/std) become lists so dumps() emits
        # plain json (reference: image.py Augmenter.__init__)
        self._kwargs = {
            k: (v.asnumpy().tolist() if isinstance(v, ndarray.NDArray)
                else v.tolist() if isinstance(v, _np.ndarray) else v)
            for k, v in kwargs.items()}

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _nprand.rand() < self.p:
            return _like(_to_host(src)[:, ::-1].copy(), src)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        if isinstance(src, ndarray.NDArray):
            return src.astype(self.typ)
        return src.astype(self.typ, copy=False)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _nprand.uniform(-self.brightness, self.brightness)
        return _like(_to_host(src).astype(_np.float32) * alpha, src)


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _nprand.uniform(-self.contrast, self.contrast)
        arr = _to_host(src).astype(_np.float32)
        gray = (arr * self._coef).sum() * (3.0 / arr.size)
        return _like(arr * alpha + gray * (1.0 - alpha), src)


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _nprand.uniform(-self.saturation, self.saturation)
        arr = _to_host(src).astype(_np.float32)
        gray = (arr * self._coef).sum(axis=2, keepdims=True)
        return _like(arr * alpha + gray * (1.0 - alpha), src)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        # yiq rotation (reference: image.py HueJitterAug)
        alpha = _nprand.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]])
        tyiq = _np.array([[0.299, 0.587, 0.114], [0.596, -0.274, -0.321],
                          [0.211, -0.523, 0.311]])
        ityiq = _np.array([[1.0, 0.956, 0.621], [1.0, -0.272, -0.647],
                           [1.0, -1.107, 1.705]])
        t = _np.dot(_np.dot(ityiq, bt), tyiq).T
        arr = _to_host(src).astype(_np.float32)
        return _like(_np.dot(arr, t).astype(_np.float32), src)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, dtype=_np.float32)
        self.eigvec = _np.asarray(eigvec, dtype=_np.float32)

    def __call__(self, src):
        alpha = _nprand.normal(0, self.alphastd, size=(3,))
        rgb = _np.dot(self.eigvec * alpha, self.eigval)
        return _like(_to_host(src).astype(_np.float32) + rgb, src)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness, contrast, saturation):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.augs = []
        if brightness > 0:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation > 0:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        for i in _nprand.permutation(len(self.augs)):
            src = self.augs[i](src)
        return src


class RandomGrayAug(Augmenter):
    """Convert to 3-channel grayscale with probability p (reference:
    image.py RandomGrayAug)."""

    _coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _nprand.rand() < self.p:
            arr = _to_host(src).astype(_np.float32)
            gray = (arr * self._coef).sum(axis=2, keepdims=True)
            return _like(_np.broadcast_to(
                gray, gray.shape[:2] + (3,)).copy(), src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for i in _nprand.permutation(len(self.ts)):
            src = self.ts[i](src)
        return src


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference: image.py
    CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(_RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0, 4.0 / 3.0),
                                           inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is not None and len(_np.atleast_1d(mean)) > 0:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class _RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__()
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, (self.area, 1.0), self.ratio,
                                self.interp)[0]


# ------------------------------------------------------------- ImageIter


class ImageIter(_io.DataIter):
    """Image data iterator with augmenters, reading .rec or an imglist
    (reference: image.py ImageIter over ImageRecordIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 part_index=0, num_parts=1, aug_list=None, imglist=None,
                 shuffle=False, data_name="data", label_name="softmax_label",
                 **kwargs):
        super().__init__(batch_size)
        assert len(data_shape) == 3
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.shuffle = shuffle
        self._data_name = data_name
        self._label_name = label_name
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)

        self.seq = None
        self.imgrec = None
        self.imglist = None
        self.path_root = path_root
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec,
                                                     "r")
            self.seq = list(self.imgrec.keys)
        elif path_imglist:
            with open(path_imglist) as f:
                result = {}
                for line in f:
                    parts = line.strip().split("\t")
                    label = _np.array(parts[1:-1], dtype=_np.float32)
                    result[int(parts[0])] = (label, parts[-1])
            self.imglist = result
            self.seq = list(result.keys())
        elif imglist is not None:
            result = {}
            for i, item in enumerate(imglist):
                result[i] = (_np.asarray(item[0], dtype=_np.float32)
                             if not _np.isscalar(item[0])
                             else _np.array([item[0]], dtype=_np.float32),
                             item[1])
            self.imglist = result
            self.seq = list(result.keys())
        else:
            raise ValueError("must supply path_imgrec, path_imglist or imglist")
        if num_parts > 1:
            self.seq = self.seq[part_index::num_parts]
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [_io.DataDesc(self._data_name,
                             (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [_io.DataDesc(self._label_name, shape)]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.seq)
        self.cur = 0

    def next_sample(self):
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            rec = self.imgrec.read_idx(idx)
            header, img = recordio.unpack(rec)
            return header.label, img
        label, fname = self.imglist[idx]
        with open(os.path.join(self.path_root or "", fname), "rb") as f:
            return label, f.read()

    def next(self):
        batch_data = _np.zeros((self.batch_size,) + self.data_shape,
                               dtype=_np.float32)
        batch_label = _np.zeros((self.batch_size, self.label_width),
                                dtype=_np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, buf = self.next_sample()
                # whole chain on host numpy; _HostArray keeps the
                # NDArray read surface for user-supplied augmenters
                img = _imdecode_np(buf).view(_HostArray)
                for aug in self.auglist:
                    img = aug(img)
                batch_data[i] = _to_host(img).transpose(2, 0, 1)
                batch_label[i] = _np.atleast_1d(label)[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        if self.label_width == 1:
            batch_label = batch_label[:, 0]
        return _io.DataBatch(
            data=[ndarray.array(batch_data)],
            label=[ndarray.array(batch_label)],
            pad=self.batch_size - i)


# detection-aware augmenters + ImageDetIter live in image_detection.py;
# surfaced here to match the reference's mx.image namespace
from .image_detection import (  # noqa: E402
    CreateDetAugmenter, CreateMultiRandCropAugmenter, DetAugmenter,
    DetBorrowAug, DetHorizontalFlipAug, DetRandomCropAug, DetRandomPadAug,
    DetRandomSelectAug, ImageDetIter)

__all__ += ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
            "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
            "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
            "ImageDetIter"]
