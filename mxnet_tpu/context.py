"""Device context — TPU-native equivalent of MXNet's Context.

Reference: include/mxnet/base.h:104 (``Context``), python/mxnet/context.py.

In the reference a Context names a (device_type, device_id) pair and every
NDArray/op dispatch routes through it (engine queues are per-context,
``src/engine/threaded_engine_perdevice.cc:93``).  Here a Context is a thin,
hashable handle onto a ``jax.Device``: placement is done with
``jax.device_put`` and XLA's async dispatch replaces the per-device worker
queues.  ``cpu()`` maps to the host platform, ``tpu()`` to the accelerator
platform (``gpu()`` is accepted as an alias for accelerator contexts so that
reference scripts run unchanged).
"""

from __future__ import annotations

import threading

__all__ = [
    "Context",
    "cpu",
    "cpu_pinned",
    "tpu",
    "gpu",
    "current_context",
    "num_gpus",
    "num_tpus",
]


class Context:
    """A device context.

    Parameters
    ----------
    device_type : {'cpu', 'tpu', 'gpu', 'cpu_pinned', 'cpu_shared'}
        'gpu' is an alias for the accelerator platform so code written
        against the reference API (``mx.gpu(0)``) keeps working on TPU.
    device_id : int
        Index into ``jax.devices(platform)``.
    """

    # mirror of the reference's DeviceType enum (include/mxnet/base.h:108)
    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "gpu"}
    devstr2type = {"cpu": 1, "tpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "gpu": 6}

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- jax bridge ------------------------------------------------------
    @property
    def jax_device(self):
        """The underlying ``jax.Device`` for this context."""
        import jax

        dt = self.device_type
        if dt in ("cpu", "cpu_pinned", "cpu_shared"):
            # local_devices: a Context is per-PROCESS (multi-process runs
            # must never place data on another rank's device)
            try:
                return jax.local_devices(backend="cpu")[self.device_id]
            except RuntimeError:
                # no host platform registered (rare); fall back to default
                return jax.local_devices()[self.device_id]
        # tpu / gpu → whatever accelerator platform is present
        devs = _accelerator_devices()
        if not devs:
            # CPU-only process (tests): accelerator contexts fall back to the
            # host platform so models still run; this mirrors reference
            # behaviour of failing only on explicit device features.
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Parity with reference Context.empty_cache (gpu mem pool flush).

        XLA owns the HBM allocator; there is no user-visible pool to flush,
        so this is a documented no-op.
        """


def _accelerator_devices():
    import jax

    devs = []
    try:
        all_devs = jax.local_devices()
    except RuntimeError:
        return devs
    for d in all_devs:
        if d.platform not in ("cpu",):
            devs.append(d)
    return devs


def cpu(device_id=0):
    """Return a CPU context."""
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    """Parity alias: pinned host memory context (host memory on TPU)."""
    return Context("cpu_pinned", device_id)


def tpu(device_id=0):
    """Return a TPU context."""
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Alias for accelerator context so reference scripts run unchanged."""
    return Context("gpu", device_id)


def num_gpus():
    """Number of accelerator devices visible (reference: MXGetGPUCount)."""
    return len(_accelerator_devices())


def num_tpus():
    return len(_accelerator_devices())


def current_context():
    """The current default context (thread-local, set via ``with ctx:``)."""
    if not hasattr(Context._default_ctx, "value"):
        # TPU-native default: prefer the accelerator if one exists.
        Context._default_ctx.value = (
            Context("tpu", 0) if _accelerator_devices() else Context("cpu", 0)
        )
    return Context._default_ctx.value
