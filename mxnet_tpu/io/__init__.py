"""``mx.io`` — data iterators (reference: python/mxnet/io/io.py, src/io/)."""

from .io import (DataBatch, DataDesc, DataIter, MNISTIter, CSVIter,  # noqa: F401
                 LibSVMIter, NDArrayIter, PrefetchingIter, ResizeIter,
                 ImageRecordIter)
