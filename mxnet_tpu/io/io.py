"""Data iterators.

Reference: python/mxnet/io/io.py (DataIter:178, DataBatch, NDArrayIter:489,
MXDataIter:788) and the C++ iterator chain in src/io/ (parser →
augmenter → BatchLoader iter_batchloader.h:42 → PrefetcherIter
iter_prefetcher.h:47).

TPU-native notes: batches are assembled host-side in numpy (cheap) and
shipped to HBM once per batch (single device_put — the analog of the
reference's PrefetcherIter double buffering is PrefetchingIter below,
which overlaps host assembly with device compute using a background
thread; XLA async dispatch overlaps the copy).
"""

from __future__ import annotations

import os
import struct
import threading
import queue as _queue
from collections import namedtuple

import numpy as _np

from time import perf_counter as _perf_counter

from .. import histogram as _histogram
from .. import profiler as _profiler
from .. import runtime_stats as _rts
from .. import stepstats as _stepstats
from ..base import MXNetError
from ..ndarray import NDArray, array

DataDesc = namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])
DataDesc.__new__.__defaults__ = (_np.float32, "NCHW")


class DataBatch:
    """One batch (reference: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter:
    """Base iterator (reference: io.py:178)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        # the for-batch-in-iter hot loop: span shows host-side batch
        # assembly time in the step anatomy (guard-first: args dict is
        # only built while recording, so the off path allocates nothing;
        # the latency histogram takes timestamps only when collecting —
        # input-wait distributions are what the cluster report compares
        # across ranks to spot a starving worker)
        hist_on = _histogram._state["on"]
        if hist_on:
            t0 = _perf_counter()
        # step-anatomy data_wait phase: a CONTAINER window, so any op
        # dispatch inside batch assembly stays attributed to its own
        # phase and batch-wait time is exclusive (stepstats.py)
        ss_on = _stepstats._state["on"]
        if ss_on:
            ss_tok = _stepstats.begin()
        with _profiler.span("io:next_batch", "io",
                            args={"iter": self.__class__.__name__}
                            if _profiler._state["running"] else None):
            batch = self.next()
        if ss_on:
            _stepstats.end("data_wait", ss_tok)
        if hist_on:
            _histogram.observe("io:next_batch", _perf_counter() - t0)
        _rts.inc("io_batches")
        return batch

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference: ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetcher (reference: io.py PrefetchingIter /
    src/io/iter_prefetcher.h — dmlc::ThreadedIter double buffering)."""

    def __init__(self, iters, rename_data=None, rename_label=None, capacity=2):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter == 1, "only one iterator is supported (parity w/ ref)"
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = iters[0].batch_size
        self._queue = _queue.Queue(maxsize=capacity)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return self.iters[0].provide_data
        return [DataDesc(self.rename_data[0].get(d.name, d.name), d.shape, d.dtype)
                if isinstance(d, DataDesc) else d for d in self.iters[0].provide_data]

    @property
    def provide_label(self):
        if self.rename_label is None:
            return self.iters[0].provide_label
        return [DataDesc(self.rename_label[0].get(l.name, l.name), l.shape, l.dtype)
                if isinstance(l, DataDesc) else l for l in self.iters[0].provide_label]

    def _start(self):
        self._stop.clear()

        def worker():
            try:
                for batch in self.iters[0]:
                    if self._stop.is_set():
                        return
                    self._queue.put(batch)
            finally:
                self._queue.put(None)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        # drain
        while self._thread.is_alive():
            try:
                self._queue.get(timeout=0.01)
            except _queue.Empty:
                pass
        self._thread.join()
        while not self._queue.empty():
            self._queue.get()
        self.iters[0].reset()
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        try:
            self._peek = self.next()
            return True
        except StopIteration:
            return False


def _init_data(data, allow_empty, default_name):
    """Normalize data/label argument into list of (name, numpy) pairs."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py:489)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.idx = _np.arange(self.num_data)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._cache_idx = None
        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.idx = self.idx[:new_n]
            self.num_data = new_n
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                -self.batch_size < self.cursor < 0:
            self.cursor = -self.batch_size + (self.cursor % self.num_data)
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        assert self.cursor < self.num_data
        end = min(self.cursor + self.batch_size, self.num_data)
        sel = self.idx[self.cursor:end]
        if len(sel) < self.batch_size:  # pad by wrapping
            pad = self.batch_size - len(sel)
            sel = _np.concatenate([sel, self.idx[:pad]])
        return [array(v[sel]) for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        end = min(self.cursor + self.batch_size, self.num_data)
        return self.idx[self.cursor:end]


def _read_idx_images(path):
    with open(path, "rb") as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, "bad MNIST image file"
        return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(num, rows, cols)


def _read_idx_labels(path):
    with open(path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        assert magic == 2049, "bad MNIST label file"
        return _np.frombuffer(f.read(), dtype=_np.uint8)


def _synthetic_mnist(n, seed=0):
    """Deterministic MNIST-like synthetic digits (this container has zero
    egress, so real MNIST may be absent).  Digits are separable: class k
    lights up a distinct 7x7 quadrant pattern + noise, so models actually
    converge — good enough for convergence tests mirroring
    tests/python/train in the reference."""
    rng = _np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(_np.uint8)
    imgs = rng.rand(n, 28, 28).astype(_np.float32) * 0.2
    for k in range(10):
        mask = labels == k
        r, c = divmod(k, 4)
        imgs[mask, r * 7:(r + 1) * 7, c * 7:(c + 1) * 7] += 0.8
    return (imgs * 255).astype(_np.uint8), labels


class MNISTIter(DataIter):
    """MNIST source iterator (reference: src/io/iter_mnist.cc:260).

    Reads idx files when present at `image`/`label` paths; falls back to
    deterministic synthetic digits (zero-egress container).
    """

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, seed=0,
                 silent=False, num_parts=1, part_index=0, **kwargs):
        super().__init__(batch_size)
        if os.path.exists(image) and os.path.exists(label):
            imgs = _read_idx_images(image)
            labels = _read_idx_labels(label)
        else:
            n = 6000 if "train" in str(image) else 1000
            imgs, labels = _synthetic_mnist(n, seed=0 if "train" in str(image) else 1)
        imgs = imgs.astype(_np.float32) / 255.0
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        else:
            imgs = imgs.reshape(len(imgs), 1, 28, 28)
        # dist-data-parallel sharding (reference: iter_mnist num_parts/part_index)
        if num_parts > 1:
            imgs = imgs[part_index::num_parts]
            labels = labels[part_index::num_parts]
        self._inner = NDArrayIter(imgs, labels.astype(_np.float32),
                                  batch_size=batch_size, shuffle=shuffle,
                                  last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class CSVIter(DataIter):
    """CSV source iterator (reference: src/io/iter_csv.cc:218)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = _np.zeros((data.shape[0],), dtype=_np.float32)
        self._inner = NDArrayIter(data, label, batch_size=batch_size,
                                  shuffle=False,
                                  last_batch_handle="pad" if round_batch else "discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class LibSVMIter(DataIter):
    """LibSVM text -> CSR batches (reference: src/io/iter_libsvm.cc).

    Indices are zero-based (reference convention).  Data batches are
    CSRNDArray; labels dense (or CSR when label_libsvm given with
    multi-dim label_shape)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 num_parts=1, part_index=0, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_shape = tuple(label_shape)
        feat_dim = int(_np.prod(self.data_shape))
        self._data, labels_inline = self._parse(data_libsvm, feat_dim)
        if label_libsvm is not None:
            # separate libsvm label file: densify its sparse rows
            ldim = int(_np.prod(self.label_shape))
            lcsr, _ = self._parse(label_libsvm, ldim)
            self._label = self._densify(lcsr)
        else:
            self._label = _np.asarray(labels_inline, dtype=_np.float32)
        if num_parts > 1:
            n = self._data["n"]
            sel = _np.arange(part_index, n, num_parts)
            self._data = self._subset(self._data, sel)
            self._label = self._label[sel]
        self.cursor = -batch_size
        self.round_batch = round_batch

    @staticmethod
    def _densify(csr):
        out = _np.zeros((csr["n"], csr["dim"]), _np.float32)
        for r in range(csr["n"]):
            lo, hi = csr["indptr"][r], csr["indptr"][r + 1]
            out[r, csr["indices"][lo:hi]] = csr["data"][lo:hi]
        return out

    @staticmethod
    def _parse(path, feat_dim):
        data, indices, indptr, labels = [], [], [0], []
        with open(path) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    indices.append(int(i))
                    data.append(float(v))
                indptr.append(len(indices))
        return {"data": _np.asarray(data, _np.float32),
                "indices": _np.asarray(indices, _np.int64),
                "indptr": _np.asarray(indptr, _np.int64),
                "n": len(indptr) - 1, "dim": feat_dim}, labels

    @staticmethod
    def _subset(csr, sel):
        data, indices, indptr = [], [], [0]
        for r in sel:
            lo, hi = csr["indptr"][r], csr["indptr"][r + 1]
            data.extend(csr["data"][lo:hi])
            indices.extend(csr["indices"][lo:hi])
            indptr.append(len(indices))
        return {"data": _np.asarray(data, _np.float32),
                "indices": _np.asarray(indices, _np.int64),
                "indptr": _np.asarray(indptr, _np.int64),
                "n": len(sel), "dim": csr["dim"]}

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         _np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_shape == (1,) else \
            (self.batch_size,) + self.label_shape
        return [DataDesc("label", shape, _np.float32)]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.round_batch:
            return self.cursor < self._data["n"]
        # round_batch=False: discard the final partial batch (same as
        # CSVIter's last_batch_handle='discard' — never wrap silently)
        return self.cursor + self.batch_size <= self._data["n"]

    def next(self):
        from ..ndarray.sparse import CSRNDArray
        from ..ndarray import array as _arr

        if not self.iter_next():
            raise StopIteration
        n = self._data["n"]
        rows = [(self.cursor + i) % n for i in range(self.batch_size)]
        pad = max(0, self.cursor + self.batch_size - n)
        sub = self._subset(self._data, _np.asarray(rows))
        data = CSRNDArray(sub["data"], sub["indices"], sub["indptr"],
                          (self.batch_size, sub["dim"]))
        label = _arr(self._label[_np.asarray(rows) % len(self._label)])
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ImageRecordIter(DataIter):
    """RecordIO image iterator (reference: src/io/iter_image_recordio_2.cc:748).

    Decodes a RecordIO file of packed images (recordio.py format),
    applies basic augmentation (crop/mirror/mean), assembles NCHW batches.

    Two execution paths, mirroring the reference's parser→batcher→prefetcher
    chain:
    - native (default when libmxtpu builds): C++ pipeline does chunked
      sharded RecordIO reads, shuffle-buffer sampling, worker-pool decode
      (JPEG fully in C++ via libjpeg when available — pipeline.cc
      DecodeJpeg, zero Python in the loop; PIL callback fallback; raw
      samples via the builtin memcpy) into recycled batch buffers
      (mxnet_tpu/native/src/pipeline.cc).
    - python fallback: load-all + per-batch decode.
    """

    def __init__(self, path_imgrec, data_shape, batch_size=1, label_width=1,
                 shuffle=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 rand_crop=False, rand_mirror=False, num_parts=1, part_index=0,
                 preprocess_threads=4, shuffle_buffer=4096, seed=0,
                 use_native=None, raw_records=False, **kwargs):
        super().__init__(batch_size)
        from ..recordio import MXIndexedRecordIO, MXRecordIO, unpack_img

        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.mean = _np.array([mean_r, mean_g, mean_b], dtype=_np.float32)
        self._unpack_img = unpack_img
        self.shuffle = shuffle
        # raw_records: payloads are raw float32 tensor bytes, decoded by
        # the C++ builtin (pipeline.cc DecodeRaw) with no Python in the
        # worker loop — the no-augment high-throughput path
        self._raw_records = raw_records
        if raw_records and (rand_crop or rand_mirror
                            or mean_r or mean_g or mean_b):
            import warnings
            warnings.warn(
                "ImageRecordIter(raw_records=True): augmentation arguments "
                "(rand_crop/rand_mirror/mean_*) are ignored on the raw "
                "memcpy path", stacklevel=2)
        self._pipe = None
        if use_native is None:
            use_native = os.environ.get("MXNET_USE_NATIVE_ITER", "1") == "1"
        if use_native:
            try:
                jpeg_cfg = None
                if not raw_records and _records_are_jpeg(path_imgrec) \
                        and _native_has_jpeg():
                    jpeg_cfg = {"rand_crop": rand_crop,
                                "rand_mirror": rand_mirror,
                                "mean": (mean_r, mean_g, mean_b)}
                self._pipe = _NativePipeline(
                    self, path_imgrec, batch_size=batch_size,
                    sample_shape=self.data_shape, label_width=label_width,
                    shuffle=shuffle_buffer if shuffle else 0, seed=seed,
                    num_workers=preprocess_threads,
                    part_index=part_index, num_parts=num_parts,
                    use_builtin_decode=raw_records, builtin_jpeg=jpeg_cfg)
            except (RuntimeError, OSError) as e:
                # toolchain/build problems only; anything else propagates.
                import warnings
                warnings.warn(
                    "ImageRecordIter: native pipeline unavailable (%s); "
                    "falling back to the in-memory Python reader" % (e,))
                self._pipe = None
        if self._pipe is not None:
            return
        self._records = []
        rec = MXRecordIO(path_imgrec, "r")
        while True:
            item = rec.read()
            if item is None:
                break
            self._records.append(item)
        rec.close()
        if num_parts > 1:
            self._records = self._records[part_index::num_parts]
        self._order = _np.arange(len(self._records))
        self.cursor = 0
        self.reset()

    def _decode_into(self, rec_bytes, data_out, label_out):
        """Decode one packed record into flat float32 CHW + label slots
        (called from C++ decode workers via ctypes)."""
        header, img = self._unpack_img(rec_bytes)
        img = self._augment(img)
        data_out[:] = img.ravel()
        label_out[:] = 0.0  # recycled buffer: clear all label slots first
        lab = header.label
        if _np.isscalar(lab) or getattr(lab, "ndim", 0) == 0:
            label_out[0] = float(lab)
        else:
            label_out[:self.label_width] = _np.asarray(
                lab, dtype=_np.float32)[:self.label_width]

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape, _np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape, _np.float32)]

    def reset(self):
        if self._pipe is not None:
            self._pipe.reset()
            return
        if self.shuffle:
            _np.random.shuffle(self._order)
        self.cursor = 0

    def _augment(self, img):
        c, h, w = self.data_shape
        if img.shape[0] != h or img.shape[1] != w:
            if self.rand_crop and img.shape[0] >= h and img.shape[1] >= w:
                y = _np.random.randint(0, img.shape[0] - h + 1)
                x = _np.random.randint(0, img.shape[1] - w + 1)
                img = img[y:y + h, x:x + w]
            else:  # center crop / pad
                img = _center_fit(img, h, w)
        if self.rand_mirror and _np.random.rand() < 0.5:
            img = img[:, ::-1]
        img = img.astype(_np.float32)
        if self.mean.any():
            img = img - self.mean
        return img.transpose(2, 0, 1)  # HWC→CHW

    def iter_next(self):
        if self._pipe is not None:
            return self._pipe.has_next()
        return self.cursor < len(self._records)

    def next(self):
        if self._pipe is not None:
            data, label, count = self._pipe.next()
            if self.label_width == 1:
                label = label.reshape(-1)
            return DataBatch(data=[array(data)], label=[array(label)],
                             pad=self.batch_size - count,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)
        if not self.iter_next():
            raise StopIteration
        # Final partial batch: pad with REAL wrapped records (reference
        # round_batch semantics — fabricated samples would bias fit());
        # pad counts the wrapped tail so score()/predict() trim it.
        count = min(self.batch_size, len(self._records) - self.cursor)
        datas = []
        labels = []
        for i in range(self.batch_size):
            pos = self.cursor + i
            if pos >= len(self._records):
                pos = pos % max(len(self._records), 1)
            item = self._records[self._order[pos]]
            if self._raw_records:
                from ..recordio import unpack

                header, payload = unpack(item)
                datas.append(_np.frombuffer(payload, dtype=_np.float32)
                             .reshape(self.data_shape))
                lab = header.label
                labels.append(float(lab) if _np.isscalar(lab)
                              or getattr(lab, "ndim", 0) == 0
                              else _np.asarray(lab, dtype=_np.float32))
                continue
            header, img = self._unpack_img(item)
            datas.append(self._augment(img))
            lab = header.label
            labels.append(float(lab) if _np.isscalar(lab) or lab.ndim == 0
                          else _np.asarray(lab, dtype=_np.float32))
        self.cursor += self.batch_size
        data = array(_np.stack(datas))
        label = array(_np.asarray(labels, dtype=_np.float32))
        return DataBatch(data=[data], label=[label],
                         pad=self.batch_size - count,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


def _native_has_jpeg():
    """Whether libmxtpu carries the in-worker JPEG decoder."""
    from .. import _native

    lib = _native.get_lib()
    try:
        return bool(lib is not None and lib.MXTPUPipelineHasJpeg())
    except AttributeError:  # stale prebuilt library
        return False


def _records_are_jpeg(path):
    """Peek at the first record's payload magic (JPEG = FF D8)."""
    from ..recordio import MXRecordIO, unpack

    try:
        rec = MXRecordIO(path, "r")
        raw = rec.read()
        rec.close()
        if raw is None:
            return False
        _, payload = unpack(raw)
        return bytes(payload[:2]) == b"\xff\xd8"
    except Exception:
        return False


class _NativePipeline:
    """ctypes wrapper over the C++ prefetching batch pipeline
    (mxnet_tpu/native/src/pipeline.cc).  Owns the decode callback: C++
    workers call back into Python per record (PIL JPEG decode + augment),
    writing straight into the recycled batch buffer."""

    def __init__(self, owner, path, batch_size, sample_shape, label_width,
                 shuffle, seed, num_workers, part_index, num_parts,
                 use_builtin_decode=False, builtin_jpeg=None):
        import ctypes

        from .. import _native

        lib = _native.get_lib()
        if lib is None:
            raise RuntimeError("native pipeline unavailable")
        self._lib = lib
        self._ct = ctypes
        self.batch_size = batch_size
        self.sample_shape = tuple(sample_shape)
        self.label_width = label_width
        self._sample_elems = int(_np.prod(self.sample_shape))
        sample_bytes = self._sample_elems * 4  # float32

        if builtin_jpeg is not None:
            # fully-native JPEG route: decode + augment inside the C++
            # worker pool (pipeline.cc DecodeJpeg) — zero Python in the
            # loop.  A Python callback rides along as the per-record
            # fallback for non-JPEG payloads in mixed .rec files
            c, h, w = self.sample_shape
            mean = builtin_jpeg.get("mean", (0.0, 0.0, 0.0))

            def _fb(_ctx, rec_ptr, rec_len, data_out, label_out):
                try:
                    rec = ctypes.string_at(rec_ptr, rec_len)
                    dv = _np.ctypeslib.as_array(data_out,
                                                (self._sample_elems * 4,))
                    lv = _np.ctypeslib.as_array(label_out, (label_width,))
                    owner._decode_into(rec, dv.view(_np.float32), lv)
                    return 0
                except Exception:
                    import traceback
                    self._decode_error = traceback.format_exc()
                    return 1

            self._fallback_cb = _native.DECODE_FN(_fb)  # keep alive
            hnd = ctypes.c_void_p()
            _native.check_call(lib.MXTPUPipelineCreateJpeg(
                path.encode(), 8 << 20, part_index, num_parts, batch_size,
                sample_bytes, label_width, shuffle, seed, num_workers, 0, 1,
                int(h), int(w), int(c),
                int(bool(builtin_jpeg.get("rand_crop"))),
                int(bool(builtin_jpeg.get("rand_mirror"))),
                float(mean[0]), float(mean[1]), float(mean[2]),
                self._fallback_cb, None,
                ctypes.byref(hnd)))
            self._h = hnd
            self._cb = None
            self._check = _native.check_call
            self._peek = None
            self._decode_error = None
            return

        if use_builtin_decode:
            # NULL fn pointer: C++ workers memcpy records directly via
            # the builtin DecodeRaw — zero Python in the loop
            self._cb = _native.DECODE_FN()
        else:
            def _cb(_ctx, rec_ptr, rec_len, data_out, label_out):
                try:
                    rec = ctypes.string_at(rec_ptr, rec_len)
                    d = _np.ctypeslib.as_array(data_out,
                                               (self._sample_elems * 4,))
                    l = _np.ctypeslib.as_array(label_out, (label_width,))
                    owner._decode_into(rec, d.view(_np.float32), l)
                    return 0
                except Exception:
                    import traceback
                    self._decode_error = traceback.format_exc()
                    return 1

            self._cb = _native.DECODE_FN(_cb)  # keep alive
        h = ctypes.c_void_p()
        _native.check_call(lib.MXTPUPipelineCreate(
            path.encode(), 8 << 20, part_index, num_parts, batch_size,
            sample_bytes, label_width, shuffle, seed, num_workers, 0, 1,
            self._cb, None, ctypes.byref(h)))
        self._h = h
        self._check = _native.check_call
        self._peek = None
        self._decode_error = None

    def _fetch(self):
        ct = self._ct
        data_p = ct.POINTER(ct.c_uint8)()
        label_p = ct.POINTER(ct.c_float)()
        count = ct.c_int()
        try:
            self._check(self._lib.MXTPUPipelineNext(
                self._h, ct.byref(data_p), ct.byref(label_p),
                ct.byref(count)))
        except RuntimeError as e:
            # surface the Python traceback captured in the decode callback
            tb, self._decode_error = self._decode_error, None
            if tb:
                raise RuntimeError(
                    "%s\ndecode callback error:\n%s" % (e, tb)) from None
            raise
        if count.value < 0:
            return None
        flat = _np.ctypeslib.as_array(
            data_p, (self.batch_size * self._sample_elems * 4,))
        data = flat.view(_np.float32)[:self.batch_size * self._sample_elems] \
            .reshape((self.batch_size,) + self.sample_shape).copy()
        lab = _np.ctypeslib.as_array(
            label_p, (self.batch_size * self.label_width,))
        label = lab.reshape(self.batch_size, self.label_width).copy()
        self._check(self._lib.MXTPUPipelineRelease(self._h, data_p, label_p))
        return data, label, count.value

    def has_next(self):
        if self._peek is None:
            self._peek = self._fetch()
        return self._peek is not None

    def next(self):
        if self._peek is not None:
            out, self._peek = self._peek, None
            return out
        out = self._fetch()
        if out is None:
            raise StopIteration
        return out

    def reset(self):
        self._peek = None
        self._decode_error = None
        self._check(self._lib.MXTPUPipelineReset(self._h))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.MXTPUPipelineFree(self._h)
                self._h = None
        except Exception:
            pass


def _center_fit(img, h, w):
    out = _np.zeros((h, w) + img.shape[2:], dtype=img.dtype)
    sy = max((img.shape[0] - h) // 2, 0)
    sx = max((img.shape[1] - w) // 2, 0)
    dy = max((h - img.shape[0]) // 2, 0)
    dx = max((w - img.shape[1]) // 2, 0)
    ch = min(h, img.shape[0])
    cw = min(w, img.shape[1])
    out[dy:dy + ch, dx:dx + cw] = img[sy:sy + ch, sx:sx + cw]
    return out
