"""Service-level objectives and error budgets for the serving path.

The request x-ray (``reqtrace.py``) answers "why was this request
slow?"; this module answers the operator's other question — "are we
inside our objective *right now*, and how fast are we burning the
budget?".  Objectives are declared via ``MXNET_TPU_SLO`` (no code
changes to add one), events are counted guard-first at the serving
accounting seams, and evaluation follows the multi-window burn-rate
method (Google SRE workbook): an error budget is ``1 - target``; the
*burn rate* over a window is ``window_error_rate / budget`` (burn 1.0
= spending exactly the budget); an alert needs BOTH a short and a long
window over threshold — the long window proves the problem is real,
the short window proves it is *still happening* — with the classic
pairs 5m/1h at burn >= 14.4 (fast: ~2% of a 30-day budget in an hour)
and 30m/6h at burn >= 6.0 (slow).

Window spans scale by ``MXNET_TPU_SLO_WINDOW_SCALE`` so tests (and
short benches) can compress hours into milliseconds without touching
the math.  Evaluation happens at snapshot time from a bounded
per-objective event ring, so diag dumps carry the verdicts and
``perfdoctor``'s ``slo-fast-burn`` / ``slo-budget-exhausted`` rules
(and the ``MXNET_TPU_AUTOPILOT_SLO`` reflex behind them) work on live
state and post-mortem dumps alike.

Objective syntax (comma-separated list)::

    MXNET_TPU_SLO=e2e:25ms:99.9,avail:99.5

- ``name:THRESHOLD:TARGET`` — latency objective: a request is *bad*
  when rejected/errored OR slower than THRESHOLD (``25ms``, ``0.5s``,
  or a bare ms number).
- ``name:TARGET`` — availability objective: a request is *bad* when
  rejected or errored (rejections ARE availability events — the
  lifecycle ring records them, so the budget math sees them).

Hot-path contract: callers guard on ``_state["on"]`` (one dict read
per request when disabled, bench-gated); ``on_request`` is guard-first
(mxlint ``DEFAULT_FEEDS``) and touches host floats only.

Environment variables
---------------------
``MXNET_TPU_SLO``               objective list (see above); empty or
    unset leaves the module off.
``MXNET_TPU_SLO_RING``          per-objective event-ring capacity
    (default 4096) — windows are evaluated over this ring, so it
    bounds both memory and lookback.
``MXNET_TPU_SLO_WINDOW_SCALE``  multiplies every window span
    (default 1.0; tests use tiny values to compress the clock).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from .log import get_logger, warn_rate_limited

__all__ = ["enable", "disable", "is_enabled", "on_request", "snapshot",
           "reset", "parse_objectives", "FAST_BURN", "SLOW_BURN",
           "MIN_EVENTS", "WINDOWS"]

# multi-window pairs: (short, long) seconds, burn threshold, label
FAST_BURN = 14.4
SLOW_BURN = 6.0
MIN_EVENTS = 32  # long-window events needed before a pair may fire
WINDOWS = (("5m", 300.0), ("1h", 3600.0), ("30m", 1800.0),
           ("6h", 21600.0))

# mxlint: disable=thread-shared-state -- single-key GIL-atomic enable flag; the guard-first contract forbids a lock on the disabled path
_state = {"on": False, "scale": 1.0, "ring_cap": 4096}
_lock = threading.Lock()
_OBJECTIVES: list = []  # mutated under _lock (enable/reset/on_request)

_logger_cache: list = []


def _logger():
    if not _logger_cache:
        _logger_cache.append(get_logger("mxnet_tpu.slo"))
    return _logger_cache[0]


def _env_int(name, default):
    try:
        return int(os.environ.get(name) or default)
    except (TypeError, ValueError):
        return int(default)


def _env_float(name, default):
    try:
        return float(os.environ.get(name) or default)
    except (TypeError, ValueError):
        return float(default)


# -------------------------------------------------------------- parsing


def _parse_threshold_ms(tok):
    """``25ms`` / ``0.5s`` / bare number (ms) → float ms, or None."""
    t = tok.strip().lower()
    try:
        if t.endswith("ms"):
            return float(t[:-2])
        if t.endswith("s"):
            return float(t[:-1]) * 1e3
        return float(t)
    except ValueError:
        return None


def parse_objectives(spec):
    """Parse an ``MXNET_TPU_SLO`` value into objective dicts; invalid
    entries are dropped with a rate-limited warning (a typo'd objective
    must never kill serving)."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        toks = part.split(":")
        name = toks[0].strip()
        threshold = None
        target = None
        if len(toks) == 2:
            target = _parse_threshold_ms(toks[1])  # bare percent
        elif len(toks) == 3:
            threshold = _parse_threshold_ms(toks[1])
            target = _parse_threshold_ms(toks[2])
            if threshold is None:
                target = None  # force the invalid branch below
        if not name or target is None or not (0.0 < target < 100.0):
            warn_rate_limited(
                _logger(), "slo:parse:%s" % part, 300,
                "MXNET_TPU_SLO entry %r is not name:THRESHOLD:TARGET "
                "or name:TARGET — dropped", part)
            continue
        out.append({"name": name,
                    "kind": "latency" if threshold is not None
                    else "availability",
                    "threshold_ms": threshold, "target": target / 100.0,
                    "good": 0, "bad": 0, "events": None})
    return out


# ------------------------------------------------------------ lifecycle


def enable(spec=None, ring=None, scale=None):
    """Install objectives (``spec`` beats ``MXNET_TPU_SLO``) and start
    counting.  No valid objective → stays off."""
    raw = os.environ.get("MXNET_TPU_SLO", "") if spec is None else spec
    objs = parse_objectives(raw)
    if not objs:
        return False
    cap = _env_int("MXNET_TPU_SLO_RING", 4096) if ring is None \
        else int(ring)
    cap = max(16, cap)
    sc = _env_float("MXNET_TPU_SLO_WINDOW_SCALE", 1.0) if scale is None \
        else float(scale)
    for ob in objs:
        ob["events"] = deque(maxlen=cap)
    with _lock:
        _OBJECTIVES[:] = objs
        _state["ring_cap"] = cap
        _state["scale"] = sc if sc > 0 else 1.0
    _state["on"] = True
    return True


def disable():
    """Stop counting (objectives and counters are kept; ``reset()``
    drops them)."""
    _state["on"] = False


def is_enabled():
    return _state["on"]


def reset():
    """Disable and drop every objective and counter (tests)."""
    _state["on"] = False
    with _lock:
        _OBJECTIVES[:] = []


# ----------------------------------------------------------- accounting


def on_request(latency_ms, ok):
    """Accounting seam — one call per finished request.  ``ok`` False
    for rejections (queue/shape/nonfinite) and execution errors;
    ``latency_ms`` None when the request never entered the pipeline.
    A latency objective additionally counts an over-threshold
    completion as bad."""
    if not _state["on"]:
        return
    now = time.monotonic()
    with _lock:
        for ob in _OBJECTIVES:
            bad = (not ok) or (ob["threshold_ms"] is not None
                               and latency_ms is not None
                               and latency_ms > ob["threshold_ms"])
            if bad:
                ob["bad"] += 1
            else:
                ob["good"] += 1
            ob["events"].append((now, bad))


# ------------------------------------------------------------ evaluation


def _window_stats(events, now, span):
    """(burn-numerator pieces) over the trailing ``span`` seconds:
    ``(total, bad)`` — events is newest-last, so walk from the tail."""
    total = bad = 0
    for t, b in reversed(events):
        if now - t > span:
            break
        total += 1
        if b:
            bad += 1
    return total, bad


def _evaluate_locked(ob, now, scale):
    budget = 1.0 - ob["target"]
    windows = {}
    for label, span in WINDOWS:
        total, bad = _window_stats(ob["events"], now, span * scale)
        rate = (bad / total) if total else 0.0
        windows[label] = {"seconds": span * scale, "events": total,
                          "bad": bad,
                          "burn": (rate / budget) if budget else 0.0}
    fast = (windows["5m"]["burn"] >= FAST_BURN
            and windows["1h"]["burn"] >= FAST_BURN
            and windows["1h"]["events"] >= MIN_EVENTS)
    slow = (windows["30m"]["burn"] >= SLOW_BURN
            and windows["6h"]["burn"] >= SLOW_BURN
            and windows["6h"]["events"] >= MIN_EVENTS)
    total = ob["good"] + ob["bad"]
    overall = (ob["bad"] / total) if total else 0.0
    remaining = 1.0 - (overall / budget) if budget else 1.0
    return {"name": ob["name"], "kind": ob["kind"],
            "threshold_ms": ob["threshold_ms"],
            "target": ob["target"], "good": ob["good"],
            "bad": ob["bad"], "total": total,
            "budget_remaining": min(1.0, remaining),
            "windows": windows, "fast_burn": fast, "slow_burn": slow}


def snapshot():
    """JSON-ready view with the burn verdicts baked in — what diag
    dumps carry and what the doctor rules read."""
    now = time.monotonic()
    with _lock:
        scale = _state["scale"]
        objs = [_evaluate_locked(ob, now, scale) for ob in _OBJECTIVES]
    if not _state["on"] and not objs:
        return {"enabled": False}
    return {"enabled": _state["on"], "window_scale": scale,
            "ring_cap": _state["ring_cap"], "objectives": objs}


def _activate_from_env():
    """Import-time arming — called by ``runtime_stats`` once its module
    globals exist (before the autopilot, which must arm last)."""
    if not os.environ.get("MXNET_TPU_SLO"):
        return False
    return enable()
