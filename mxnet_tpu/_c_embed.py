"""Embedded-interpreter half of the tensor-runtime C ABI (mxtpu/c_api.h).

`native/src/c_api_tensor.cc` is a logic-free transport: each extern
formats its raw argument addresses into a call on this module, which
performs ALL marshalling — reading C arrays, writing out-parameters,
pinning returned storage — with ctypes.  The semantics are the Python
package's own (NDArray, Symbol, Executor, KVStore, ...), so the C ABI
and the Python API can never drift apart.

Conventions (see the header for the consumer-facing contract):
  * handles are uint64 ids into `_handles`; 0 is never valid;
  * every entry point is no-raise: the @capi decorator reports errors
    through the trailing (status, errbuf, errcap) out-parameters that
    embed.cc appends to every call;
  * pointers returned to C (strings, arrays, nested shape data) point
    into per-thread pinned ctypes buffers kept alive for the next 256
    ABI calls on that thread (reference analog: the per-thread
    MXAPIThreadLocalEntry return store, invalidated by the next call).

Reference: include/mxnet/c_api.h (196 functions), src/c_api/*.cc.
"""

from __future__ import annotations

import ast
import collections
import ctypes
import functools
import threading
import traceback

_handles: dict[int, object] = {}
_next_id = [1]

_PIN_CAP = 256
_tls = threading.local()


# ------------------------------------------------------------- registry --
_handle_lock = threading.Lock()


def _new_handle(obj) -> int:
    with _handle_lock:  # concurrent C threads must never share an id
        hid = _next_id[0]
        _next_id[0] += 1
    _handles[hid] = obj
    return hid


def _obj(hid):
    try:
        return _handles[int(hid)]
    except KeyError:
        raise ValueError("invalid or freed MXTPUHandle %d" % hid) from None


def _free_handle(hid):
    _handles.pop(int(hid), None)


# ------------------------------------------------------------ pin store --
# One deque entry per ABI *call* (a list of that call's buffers), so the
# documented "valid for 256 further ABI calls" contract holds no matter
# how many buffers a single call pins (InferShape on a 400-arg net pins
# one per shape).
def _pin(buf):
    group = getattr(_tls, "call_pins", None)
    if group is not None:
        group.append(buf)
        return buf
    store = getattr(_tls, "pins", None)
    if store is None:
        store = _tls.pins = collections.deque(maxlen=_PIN_CAP)
    store.append([buf])
    return buf


def _pin_bytes(b: bytes) -> int:
    buf = _pin(ctypes.create_string_buffer(b, len(b) + 1))
    return ctypes.addressof(buf)


def _pin_str(s: str) -> int:
    return _pin_bytes(s.encode("utf-8"))


def _pin_str_array(strs) -> int:
    bufs = [ctypes.create_string_buffer(s.encode("utf-8")) for s in strs]
    arr = (ctypes.c_char_p * max(1, len(strs)))()
    for i, b in enumerate(bufs):
        arr[i] = ctypes.cast(b, ctypes.c_char_p)
    _pin(bufs)
    _pin(arr)
    return ctypes.addressof(arr)


def _pin_array(ctype, vals) -> int:
    arr = (ctype * max(1, len(vals)))(*vals)
    _pin(arr)
    return ctypes.addressof(arr)


# ------------------------------------------------------- read/write raw --
def _read_u32_array(addr, n):
    if not addr or not n:
        return []
    p = ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_uint32))
    return [int(p[i]) for i in range(n)]


def _read_i32_array(addr, n):
    if not addr or not n:
        return []
    p = ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_int32))
    return [int(p[i]) for i in range(n)]


def _read_i64_array(addr, n):
    if not addr or not n:
        return []
    p = ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_int64))
    return [int(p[i]) for i in range(n)]


def _read_u64_array(addr, n):
    if not addr or not n:
        return []
    p = ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_uint64))
    return [int(p[i]) for i in range(n)]


def _read_f32_array(addr, n):
    if not addr or not n:
        return []
    p = ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_float))
    return [float(p[i]) for i in range(n)]


def _read_str(addr):
    return ctypes.string_at(int(addr)).decode("utf-8") if addr else None


def _read_str_array(addr, n):
    if not addr or not n:
        return []
    p = ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_char_p))
    return [p[i].decode("utf-8") if p[i] is not None else None
            for i in range(n)]


def _write(ctype, addr, val):
    if addr:
        ctypes.cast(int(addr), ctypes.POINTER(ctype))[0] = val


def _write_u64(addr, val):
    _write(ctypes.c_uint64, addr, int(val))


def _write_u32(addr, val):
    _write(ctypes.c_uint32, addr, int(val))


def _write_i32(addr, val):
    _write(ctypes.c_int32, addr, int(val))


def _read_i32(addr):
    return int(ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_int32))[0])


# -------------------------------------------------------- capi decorator --
def _status(status_addr, err_addr, err_cap, code, msg=""):
    if err_addr and msg:
        raw = msg.encode("utf-8", "replace")[: max(0, err_cap - 1)] + b"\0"
        ctypes.memmove(int(err_addr), raw, len(raw))
    ctypes.cast(int(status_addr),
                ctypes.POINTER(ctypes.c_int64))[0] = code


def capi(fn):
    """No-raise wrapper: strip the trailing (status, errbuf, errcap)
    appended by embed.cc, report exceptions through them."""

    @functools.wraps(fn)
    def wrapper(*args):
        status_addr, err_addr, err_cap = args[-3:]
        group = _tls.call_pins = []
        try:
            fn(*args[:-3])
            _status(status_addr, err_addr, err_cap, 0)
        except BaseException:
            _status(status_addr, err_addr, err_cap, -1,
                    traceback.format_exc())
        finally:
            _tls.call_pins = None
            store = getattr(_tls, "pins", None)
            if store is None:
                store = _tls.pins = collections.deque(maxlen=_PIN_CAP)
            if group:
                store.append(group)

    return wrapper


# ------------------------------------------------------- value parsing  --
def _parse_param(s):
    """C params arrive as strings (reference convention); recover python
    values: numbers, tuples, lists, booleans; bare words stay strings."""
    if s is None:
        return None
    low = s.strip().lower()
    if low == "true":
        return True
    if low == "false":
        return False
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _parse_params(num, keys_addr, vals_addr):
    keys = _read_str_array(keys_addr, num)
    vals = _read_str_array(vals_addr, num)
    return {k: _parse_param(v) for k, v in zip(keys, vals)}


def _ctx(dev_type, dev_id):
    from . import context as _context

    if dev_type == 2:
        return _context.tpu(dev_id)
    if dev_type == 3:
        return _context.cpu_pinned(dev_id)
    return _context.cpu(dev_id)


def _dev_code(ctx):
    return {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3}.get(
        ctx.device_type, 1)


def _np_dtype_of_code(code):
    from .base import _DTYPE_MX_TO_NP

    return _DTYPE_MX_TO_NP[int(code)]


def _code_of_np_dtype(dt):
    from .base import _DTYPE_NP_TO_MX, np_dtype

    return _DTYPE_NP_TO_MX[np_dtype(dt)]


class _EmptyND:
    """Placeholder behind MXTPUNDArrayCreateNone until first write
    (reference: an empty NDArray filled by imperative ops)."""


def _write_into(hid, val):
    """Write a result into a caller-provided NDArray handle, preserving
    Python-object aliasing the way the Python package's x[:] = v does."""
    dst = _handles[int(hid)]
    if isinstance(dst, _EmptyND):
        _handles[int(hid)] = val
    else:
        dst[:] = val


def _nd_mod():
    from . import ndarray

    return ndarray


# ================================================================== base --
@capi
def get_version(out_addr):
    from . import __version__

    parts = (__version__.split("+")[0].split(".") + ["0", "0"])[:3]
    _write_i32(out_addr, int(parts[0]) * 10000 + int(parts[1]) * 100 +
               int(parts[2]))


@capi
def random_seed(seed):
    from . import random as _random

    _random.seed(int(seed))


@capi
def random_seed_context(seed, dev_type, dev_id):
    from . import random as _random

    _random.seed(int(seed), ctx=_ctx(dev_type, dev_id))


@capi
def notify_shutdown():
    _nd_mod().waitall()


_omp_threads = [0]


@capi
def set_num_omp_threads(n):
    # XLA owns device threading; record the host hint (reference:
    # MXSetNumOMPThreads → omp_set_num_threads).
    import os

    _omp_threads[0] = int(n)
    os.environ["OMP_NUM_THREADS"] = str(int(n))


_bulk_size = [15]  # reference default MXNET_ENGINE_BULK_EXEC_MAX_NODE


@capi
def engine_set_bulk_size(size, prev_addr):
    _write_i32(prev_addr, _bulk_size[0])
    _bulk_size[0] = int(size)


@capi
def get_device_count(out_addr):
    import jax

    n = sum(1 for d in jax.devices() if d.platform != "cpu")
    _write_i32(out_addr, n)


@capi
def get_device_memory_information(dev_id, free_addr, total_addr):
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    stats = {}
    try:
        stats = devs[int(dev_id)].memory_stats() or {}
    except Exception:
        pass
    total = int(stats.get("bytes_limit", 0))
    used = int(stats.get("bytes_in_use", 0))
    _write(ctypes.c_uint64, free_addr, max(0, total - used))
    _write(ctypes.c_uint64, total_addr, total)


@capi
def lib_info_features(out_names_addr, out_enabled_addr, out_size_addr):
    from . import runtime

    feats = runtime.feature_list()
    _write_u64(out_names_addr, _pin_str_array([f.name for f in feats]))
    _write_u64(out_enabled_addr,
               _pin_array(ctypes.c_int32, [int(f.enabled) for f in feats]))
    _write(ctypes.c_uint64, out_size_addr, len(feats))


# =============================================================== ndarray --
@capi
def nd_create_none(out_addr):
    _write_u64(out_addr, _new_handle(_EmptyND()))


@capi
def nd_create(shape_addr, ndim, dev_type, dev_id, delay_alloc, dtype,
              out_addr):
    del delay_alloc  # XLA/PJRT allocates lazily by construction
    shape = tuple(_read_u32_array(shape_addr, ndim))
    arr = _nd_mod().zeros(shape, ctx=_ctx(dev_type, dev_id),
                          dtype=_np_dtype_of_code(dtype))
    _write_u64(out_addr, _new_handle(arr))


@capi
def nd_free(hid):
    _free_handle(hid)


@capi
def nd_get_shape(hid, out_ndim_addr, out_pdata_addr):
    o = _obj(hid)
    shape = () if isinstance(o, _EmptyND) else tuple(o.shape)
    _write_u32(out_ndim_addr, len(shape))
    _write_u64(out_pdata_addr, _pin_array(ctypes.c_uint32, list(shape)))


@capi
def nd_get_dtype(hid, out_addr):
    o = _obj(hid)
    if isinstance(o, _EmptyND):
        _write_i32(out_addr, -1)
    else:
        _write_i32(out_addr, _code_of_np_dtype(o.dtype))


@capi
def nd_get_context(hid, out_dev_type_addr, out_dev_id_addr):
    o = _obj(hid)
    ctx = o.context
    _write_i32(out_dev_type_addr, _dev_code(ctx))
    _write_i32(out_dev_id_addr, ctx.device_id)


@capi
def nd_get_data(hid, out_addr):
    import numpy as np

    o = _obj(hid)
    snap = _pin(np.ascontiguousarray(o.asnumpy()))
    _write_u64(out_addr, snap.ctypes.data)


@capi
def nd_sync_copy_from_cpu(hid, data_addr, size):
    import numpy as np

    o = _obj(hid)
    if isinstance(o, _EmptyND):
        raise ValueError("SyncCopyFromCPU: array has no shape yet "
                         "(created with CreateNone)")
    dt = np.dtype(o.dtype)
    n = int(size)
    if n != int(np.prod(o.shape, dtype=np.int64)):
        raise ValueError("SyncCopyFromCPU: size %d != array elements %d"
                         % (n, int(np.prod(o.shape, dtype=np.int64))))
    raw = ctypes.string_at(int(data_addr), n * dt.itemsize)
    vals = np.frombuffer(raw, dtype=dt).reshape(o.shape)
    o[:] = vals


@capi
def nd_sync_copy_to_cpu(hid, data_addr, size):
    import numpy as np

    o = _obj(hid)
    vals = np.ascontiguousarray(o.asnumpy())
    n = int(size)
    if n != vals.size:
        raise ValueError("SyncCopyToCPU: size %d != array elements %d"
                         % (n, vals.size))
    ctypes.memmove(int(data_addr), vals.ctypes.data, vals.nbytes)


@capi
def nd_sync_copy_from_ndarray(dst_hid, src_hid, i):
    src = _obj(src_hid)
    if int(i) >= 0:
        src = _aux_ndarray(src, int(i))
    _write_into(dst_hid, src)


@capi
def nd_slice(hid, begin, end, out_addr):
    o = _obj(hid)
    _write_u64(out_addr, _new_handle(o[int(begin):int(end)]))


@capi
def nd_at(hid, idx, out_addr):
    o = _obj(hid)
    _write_u64(out_addr, _new_handle(o[int(idx)]))


@capi
def nd_reshape(hid, ndim, dims_addr, reverse, out_addr):
    o = _obj(hid)
    dims = tuple(_read_i32_array(dims_addr, ndim))
    out = (o.reshape(dims, reverse=True) if reverse
           else o.reshape(dims))
    _write_u64(out_addr, _new_handle(out))


@capi
def nd_reshape64(hid, ndim, dims_addr, reverse, out_addr):
    o = _obj(hid)
    dims = tuple(_read_i64_array(dims_addr, ndim))
    out = (o.reshape(dims, reverse=True) if reverse
           else o.reshape(dims))
    _write_u64(out_addr, _new_handle(out))


@capi
def nd_detach(hid, out_addr):
    _write_u64(out_addr, _new_handle(_obj(hid).detach()))


@capi
def nd_set_grad_state(hid, state):
    # "fresh gradient" marker (reference: NDArray::set_fresh_out_grad)
    _obj(hid)._fresh_grad = bool(state)


@capi
def nd_get_grad_state(hid, out_addr):
    _write_i32(out_addr, int(getattr(_obj(hid), "_fresh_grad", False)))


@capi
def nd_get_grad(hid, out_addr):
    g = getattr(_obj(hid), "grad", None)
    _write_u64(out_addr, _new_handle(g) if g is not None else 0)


@capi
def nd_wait_to_read(hid):
    _obj(hid).wait_to_read()


@capi
def nd_wait_to_write(hid):
    _obj(hid).wait_to_read()


@capi
def nd_wait_all():
    _nd_mod().waitall()


@capi
def nd_save(fname_addr, num, args_addr, keys_addr):
    handles = _read_u64_array(args_addr, num)
    arrs = [_obj(h) for h in handles]
    keys = _read_str_array(keys_addr, num) if keys_addr else None
    data = dict(zip(keys, arrs)) if keys else arrs
    _nd_mod().save(_read_str(fname_addr), data)


def _return_loaded(loaded, out_size_addr, out_arr_addr, out_name_size_addr,
                   out_names_addr):
    if isinstance(loaded, dict):
        names = list(loaded.keys())
        arrs = [loaded[k] for k in names]
    else:
        names = []
        arrs = list(loaded)
    hids = [_new_handle(a) for a in arrs]
    _write_u32(out_size_addr, len(hids))
    _write_u64(out_arr_addr, _pin_array(ctypes.c_uint64, hids))
    _write_u32(out_name_size_addr, len(names))
    _write_u64(out_names_addr, _pin_str_array(names))


@capi
def nd_load(fname_addr, out_size_addr, out_arr_addr, out_name_size_addr,
            out_names_addr):
    _return_loaded(_nd_mod().load(_read_str(fname_addr)), out_size_addr,
                   out_arr_addr, out_name_size_addr, out_names_addr)


@capi
def nd_load_from_buffer(buf_addr, size, out_size_addr, out_arr_addr,
                        out_name_size_addr, out_names_addr):
    buf = ctypes.string_at(int(buf_addr), int(size))
    _return_loaded(_nd_mod().load_frombuffer(buf), out_size_addr,
                   out_arr_addr, out_name_size_addr, out_names_addr)


@capi
def nd_save_raw_bytes(hid, out_size_addr, out_buf_addr):
    # Single-array serialization reuses the container format with one
    # positional entry (this framework's raw-bytes format; the
    # reference's is likewise its own binary layout).
    import io as _io

    import numpy as np

    o = _obj(hid)
    bio = _io.BytesIO()
    np.savez(bio, data=o.asnumpy())
    raw = bio.getvalue()
    _write(ctypes.c_uint64, out_size_addr, len(raw))
    _write_u64(out_buf_addr, _pin_bytes(raw))


@capi
def nd_load_from_raw_bytes(buf_addr, size, out_addr):
    import io as _io

    import numpy as np

    raw = ctypes.string_at(int(buf_addr), int(size))
    with np.load(_io.BytesIO(raw)) as z:
        arr = _nd_mod().array(z["data"])
    _write_u64(out_addr, _new_handle(arr))


_STYPE_CODES = {"default": 0, "row_sparse": 1, "csr": 2}


@capi
def nd_get_storage_type(hid, out_addr):
    o = _obj(hid)
    st = getattr(o, "stype", "default")
    _write_i32(out_addr, _STYPE_CODES.get(st, 0))


@capi
def nd_create_sparse(storage_type, shape_addr, ndim, dev_type, dev_id,
                     delay_alloc, dtype, num_aux, aux_type_addr,
                     aux_ndims_addr, aux_shape_addr, out_addr):
    del delay_alloc, num_aux, aux_type_addr, aux_ndims_addr, aux_shape_addr
    from .ndarray import sparse as _sparse

    shape = tuple(_read_u32_array(shape_addr, ndim))
    stype = {1: "row_sparse", 2: "csr"}.get(int(storage_type))
    if stype is None:
        raise ValueError("CreateSparseEx: storage_type %d is not sparse"
                         % storage_type)
    arr = _sparse.zeros(stype, shape, ctx=_ctx(dev_type, dev_id),
                        dtype=_np_dtype_of_code(dtype))
    _write_u64(out_addr, _new_handle(arr))


def _aux_ndarray(o, i):
    st = getattr(o, "stype", "default")
    if st == "row_sparse":
        order = [o.indices]
    elif st == "csr":
        order = [o.indptr, o.indices]
    else:
        raise ValueError("dense NDArray has no aux array %d" % i)
    return order[i]


@capi
def nd_get_aux_type(hid, i, out_addr):
    aux = _aux_ndarray(_obj(hid), int(i))
    _write_i32(out_addr, _code_of_np_dtype(aux.dtype))


@capi
def nd_get_aux_ndarray(hid, i, out_addr):
    _write_u64(out_addr, _new_handle(_aux_ndarray(_obj(hid), int(i))))


@capi
def nd_get_data_ndarray(hid, out_addr):
    o = _obj(hid)
    if getattr(o, "stype", "default") == "default":
        raise ValueError("GetDataNDArray: dense NDArray has no data aux")
    _write_u64(out_addr, _new_handle(o.data))


@capi
def nd_sync_check_format(hid, full_check):
    o = _obj(hid)
    fn = getattr(o, "check_format", None)
    if fn is not None:
        fn(full_check=bool(full_check))


# DLPack structs (dlpack/dlpack.h v0.x ABI, as the reference exports)
class _DLDevice(ctypes.Structure):
    _fields_ = [("device_type", ctypes.c_int32),
                ("device_id", ctypes.c_int32)]


class _DLDataType(ctypes.Structure):
    _fields_ = [("code", ctypes.c_uint8), ("bits", ctypes.c_uint8),
                ("lanes", ctypes.c_uint16)]


class _DLTensor(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p), ("device", _DLDevice),
                ("ndim", ctypes.c_int32), ("dtype", _DLDataType),
                ("shape", ctypes.POINTER(ctypes.c_int64)),
                ("strides", ctypes.POINTER(ctypes.c_int64)),
                ("byte_offset", ctypes.c_uint64)]


class _DLManagedTensor(ctypes.Structure):
    pass


_DLDeleterFn = ctypes.CFUNCTYPE(None, ctypes.POINTER(_DLManagedTensor))
_DLManagedTensor._fields_ = [("dl_tensor", _DLTensor),
                             ("manager_ctx", ctypes.c_void_p),
                             ("deleter", _DLDeleterFn)]

_dlpack_exports: dict[int, tuple] = {}


def _dl_deleter(mt_ptr):
    _dlpack_exports.pop(ctypes.addressof(mt_ptr.contents), None)


_dl_deleter_c = _DLDeleterFn(_dl_deleter)

_DL_CODE_OF_KIND = {"i": 0, "u": 1, "f": 2, "b": 1}


@capi
def nd_to_dlpack(hid, out_addr):
    import numpy as np

    o = _obj(hid)
    snap = np.ascontiguousarray(o.asnumpy())
    dt = snap.dtype
    shape_arr = (ctypes.c_int64 * max(1, snap.ndim))(*snap.shape)
    mt = _DLManagedTensor()
    mt.dl_tensor.data = snap.ctypes.data
    mt.dl_tensor.device = _DLDevice(1, 0)  # kDLCPU (host snapshot)
    mt.dl_tensor.ndim = snap.ndim
    mt.dl_tensor.dtype = _DLDataType(_DL_CODE_OF_KIND[dt.kind],
                                     dt.itemsize * 8, 1)
    mt.dl_tensor.shape = shape_arr
    mt.dl_tensor.strides = None
    mt.dl_tensor.byte_offset = 0
    mt.manager_ctx = None
    mt.deleter = _dl_deleter_c
    addr = ctypes.addressof(mt)
    _dlpack_exports[addr] = (mt, snap, shape_arr)  # keep alive until deleter
    _write_u64(out_addr, addr)


@capi
def nd_from_dlpack(mt_addr, out_addr):
    import numpy as np

    mt = ctypes.cast(int(mt_addr),
                     ctypes.POINTER(_DLManagedTensor)).contents
    t = mt.dl_tensor
    if t.device.device_type not in (1, 3):  # kDLCPU / kDLCPUPinned
        raise ValueError("FromDLPack: only host DLTensors are supported")
    shape = [t.shape[i] for i in range(t.ndim)]
    kind = {0: "i", 1: "u", 2: "f", 4: "V"}.get(t.dtype.code)
    if kind is None or t.dtype.lanes != 1:
        raise ValueError("FromDLPack: unsupported dtype code %d/lanes %d"
                         % (t.dtype.code, t.dtype.lanes))
    dt = np.dtype("%s%d" % (kind, t.dtype.bits // 8))
    if t.strides:
        strides = [t.strides[i] * dt.itemsize for i in range(t.ndim)]
    else:
        strides = None
    if strides:
        # a strided view can span a larger parent buffer: copy the full
        # extent [0, sum((dim-1)*stride) + itemsize) before re-striding
        extent = dt.itemsize + sum((d - 1) * st
                                   for d, st in zip(shape, strides) if d > 0)
        raw = ctypes.string_at(t.data + t.byte_offset, max(1, extent))
        # gather element bytes through a byte-level strided view (the
        # copied extent may be misaligned for dt at stride boundaries)
        vals = np.lib.stride_tricks.as_strided(
            np.frombuffer(raw, dtype=np.uint8),
            shape=tuple(shape) + (dt.itemsize,),
            strides=tuple(strides) + (1,)).copy().view(dt).reshape(shape)
    else:
        n_bytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        raw = ctypes.string_at(t.data + t.byte_offset, max(1, n_bytes))
        vals = np.frombuffer(raw, dtype=dt).reshape(shape)
    arr = _nd_mod().array(vals)
    if mt.deleter:
        mt.deleter(ctypes.cast(int(mt_addr),
                               ctypes.POINTER(_DLManagedTensor)))
    _write_u64(out_addr, _new_handle(arr))


@capi
def nd_call_dlpack_deleter(mt_addr):
    mt = ctypes.cast(int(mt_addr),
                     ctypes.POINTER(_DLManagedTensor)).contents
    if mt.deleter:
        mt.deleter(ctypes.cast(int(mt_addr),
                               ctypes.POINTER(_DLManagedTensor)))


_shm_exports: dict[int, object] = {}
_shm_next = [1]


def _shm_cleanup():
    for shm in _shm_exports.values():
        try:
            shm.close()
            shm.unlink()
        except Exception:
            pass
    _shm_exports.clear()


import atexit  # noqa: E402  (co-located with the registry it empties)

atexit.register(_shm_cleanup)


@capi
def nd_get_shared_mem_handle(hid, pid_addr, id_addr):
    # Copy-out into POSIX shm (reference shares the buffer zero-copy;
    # PJRT owns ours, so the shared segment is a synced snapshot).
    import os
    from multiprocessing import shared_memory

    import numpy as np

    o = _obj(hid)
    snap = np.ascontiguousarray(o.asnumpy())
    sid = _shm_next[0]
    _shm_next[0] += 1
    shm = shared_memory.SharedMemory(
        name="mxtpu_%d_%d" % (os.getpid(), sid), create=True,
        size=max(1, snap.nbytes))
    shm.buf[: snap.nbytes] = snap.tobytes()
    _shm_exports[sid] = shm  # keep mapped; freed at process exit
    _write_i32(pid_addr, os.getpid())
    _write_i32(id_addr, sid)


@capi
def nd_create_from_shared_mem(shared_pid, shared_id, shape_addr, ndim, dtype,
                              out_addr):
    from multiprocessing import shared_memory

    import numpy as np

    shape = tuple(_read_u32_array(shape_addr, ndim))
    dt = np.dtype(_np_dtype_of_code(dtype))
    shm = shared_memory.SharedMemory(
        name="mxtpu_%d_%d" % (int(shared_pid), int(shared_id)))
    try:
        n = int(np.prod(shape, dtype=np.int64))
        vals = np.frombuffer(shm.buf, dtype=dt, count=n).reshape(shape).copy()
    finally:
        shm.close()
    _write_u64(out_addr, _new_handle(_nd_mod().array(vals)))


# ================================================== ops & imperative call --
def _registry():
    from .ops import registry

    return registry


@capi
def list_all_op_names(out_size_addr, out_array_addr):
    names = sorted(_registry().list_ops())
    _write_u32(out_size_addr, len(names))
    _write_u64(out_array_addr, _pin_str_array(names))


_op_handles: dict[str, int] = {}


def _op_handle(name):
    if name not in _op_handles:
        _op_handles[name] = _new_handle(_registry().get(name))
    return _op_handles[name]


@capi
def get_op_handle(name_addr, out_addr):
    name = _read_str(name_addr)
    _registry().get(name)  # raises for unknown ops
    _write_u64(out_addr, _op_handle(name))


@capi
def list_functions(out_size_addr, out_array_addr):
    names = sorted(_registry().list_ops())
    hids = [_op_handle(n) for n in names]
    _write_u32(out_size_addr, len(hids))
    _write_u64(out_array_addr, _pin_array(ctypes.c_uint64, hids))


def _op_info(op):
    name = op.name
    doc = (getattr(op.fn, "__doc__", None) or "").strip()
    desc = doc.split("\n")[0] if doc else ""
    args = list(getattr(op, "defaults", {}) or {})
    types = []
    for k in args:
        d = op.defaults[k]
        types.append("required" if d is None else "optional, default=%r" % (d,))
    descs = ["" for _ in args]
    return name, desc, args, types, descs


@capi
def get_op_info(op_hid, name_addr, desc_addr, num_args_addr, arg_names_addr,
                arg_types_addr, arg_descs_addr, return_type_addr):
    name, desc, args, types, descs = _op_info(_obj(op_hid))
    _write_u64(name_addr, _pin_str(name))
    _write_u64(desc_addr, _pin_str(desc))
    _write_u32(num_args_addr, len(args))
    _write_u64(arg_names_addr, _pin_str_array(args))
    _write_u64(arg_types_addr, _pin_str_array(types))
    _write_u64(arg_descs_addr, _pin_str_array(descs))
    _write_u64(return_type_addr, _pin_str("NDArray-or-Symbol"))


def _invoke_op(op, inputs, attrs):
    """Invoke through the nd-level registered function when it exists
    (keeps autograd recording identical to Python users), falling back
    to the raw registry."""
    nd = _nd_mod()
    fn = getattr(nd, op.name, None)
    if fn is None and op.name.startswith("_"):
        fn = getattr(nd, op.name.lstrip("_"), None)
    if fn is not None and callable(fn):
        res = fn(*inputs, **attrs)
    else:
        res = _registry().apply_op(op.name, *inputs, **attrs)
    return list(res) if isinstance(res, (list, tuple)) else [res]


@capi
def imperative_invoke(op_hid, num_inputs, inputs_addr, num_outputs_addr,
                      outputs_addr, num_params, keys_addr, vals_addr):
    op = _obj(op_hid)
    inputs = [_obj(h) for h in _read_u64_array(inputs_addr, num_inputs)]
    attrs = _parse_params(num_params, keys_addr, vals_addr)
    attrs.pop("name", None)  # graph-name hint, meaningless imperatively
    outs = _invoke_op(op, inputs, attrs)
    n_req = _read_i32(num_outputs_addr)
    if n_req == 0 or not outputs_addr:
        hids = [_new_handle(o) for o in outs]
        _write_i32(num_outputs_addr, len(hids))
        _write_u64(outputs_addr, _pin_array(ctypes.c_uint64, hids))
    else:
        if n_req != len(outs):
            raise ValueError("ImperativeInvoke: op %s produced %d outputs, "
                             "caller provided %d" % (op.name, len(outs),
                                                     n_req))
        dst_arr_addr = int(
            ctypes.cast(int(outputs_addr),
                        ctypes.POINTER(ctypes.c_uint64))[0])
        dst = _read_u64_array(dst_arr_addr, n_req)
        for h, o in zip(dst, outs):
            _write_into(h, o)


@capi
def func_invoke(op_hid, use_addr, scalar_addr, mutate_addr, num_use,
                num_scalar, num_mutate, num_params, keys_addr, vals_addr):
    op = _obj(op_hid)
    inputs = [_obj(h) for h in _read_u64_array(use_addr, num_use)]
    attrs = _parse_params(num_params, keys_addr, vals_addr)
    scalars = _read_f32_array(scalar_addr, num_scalar)
    if scalars:
        takes_scalar = ("scalar" in (op.defaults or {}) or
                        "scalar" in (getattr(op, "traced_attrs", ()) or ()))
        if len(scalars) == 1 and takes_scalar:
            attrs.setdefault("scalar", scalars[0])
        else:
            raise ValueError("FuncInvoke: op %s does not take %d scalar "
                             "args" % (op.name, len(scalars)))
    outs = _invoke_op(op, inputs, attrs)
    muts = _read_u64_array(mutate_addr, num_mutate)
    if len(muts) != len(outs):
        raise ValueError("FuncInvoke: op %s produced %d outputs, caller "
                         "provided %d mutate vars" % (op.name, len(outs),
                                                      len(muts)))
    for h, o in zip(muts, outs):
        _write_into(h, o)


# =============================================================== autograd --
def _autograd():
    from . import autograd

    return autograd


@capi
def autograd_set_is_recording(flag, prev_addr):
    ag = _autograd()
    _write_i32(prev_addr, int(ag.is_recording()))
    ag.set_recording(bool(flag))


@capi
def autograd_set_is_training(flag, prev_addr):
    ag = _autograd()
    _write_i32(prev_addr, int(ag.is_training()))
    ag.set_training(bool(flag))


@capi
def autograd_is_recording(out_addr):
    _write_i32(out_addr, int(_autograd().is_recording()))


@capi
def autograd_is_training(out_addr):
    _write_i32(out_addr, int(_autograd().is_training()))


_GRAD_REQ_NAMES = {0: "null", 1: "write", 2: "write", 3: "add"}


@capi
def autograd_mark_variables(num, var_addr, reqs_addr, grad_addr):
    variables = [_obj(h) for h in _read_u64_array(var_addr, num)]
    grads = [_obj(h) for h in _read_u64_array(grad_addr, num)]
    reqs = [_GRAD_REQ_NAMES[c] for c in _read_u32_array(reqs_addr, num)]
    _autograd().mark_variables(variables, grads, reqs)


@capi
def autograd_backward(num_output, outputs_addr, ograds_addr, num_variables,
                      vars_addr, retain_graph, create_graph, is_train,
                      grad_handles_addr, grad_stypes_addr):
    ag = _autograd()
    heads = [_obj(h) for h in _read_u64_array(outputs_addr, num_output)]
    ograd_ids = _read_u64_array(ograds_addr, num_output)
    ograds = ([None if h == 0 else _obj(h) for h in ograd_ids]
              if ograd_ids else None)
    if num_variables:
        variables = [_obj(h) for h in _read_u64_array(vars_addr,
                                                      num_variables)]
        grads = ag.grad(heads, variables, head_grads=ograds,
                        retain_graph=bool(retain_graph),
                        create_graph=bool(create_graph),
                        train_mode=bool(is_train))
        hids = [_new_handle(g) for g in grads]
        _write_u64(grad_handles_addr, _pin_array(ctypes.c_uint64, hids))
        _write_u64(grad_stypes_addr,
                   _pin_array(ctypes.c_int32, [0] * len(hids)))
    else:
        ag.backward(heads, head_grads=ograds,
                    retain_graph=bool(retain_graph),
                    train_mode=bool(is_train))


@capi
def autograd_get_symbol(hid, out_addr):
    sym = _autograd().get_symbol(_obj(hid))
    _write_u64(out_addr, _new_handle(sym))


# ================================================================= symbol --
def _sym_mod():
    from . import symbol

    return symbol


class _AtomicSymbol:
    """Uncomposed op symbol: CreateAtomicSymbol output, becomes a real
    Symbol when Compose provides its inputs (reference two-phase
    protocol: MXSymbolCreateAtomicSymbol then MXSymbolCompose)."""

    def __init__(self, op, attrs):
        self.op = op
        self.attrs = attrs


@capi
def sym_get_atomic_symbol_name(creator_hid, name_addr):
    _write_u64(name_addr, _pin_str(_obj(creator_hid).name))


@capi
def sym_get_atomic_symbol_info(creator_hid, name_addr, desc_addr,
                               num_args_addr, arg_names_addr, arg_types_addr,
                               arg_descs_addr, key_var_num_args_addr,
                               return_type_addr):
    name, desc, args, types, descs = _op_info(_obj(creator_hid))
    _write_u64(name_addr, _pin_str(name))
    _write_u64(desc_addr, _pin_str(desc))
    _write_u32(num_args_addr, len(args))
    _write_u64(arg_names_addr, _pin_str_array(args))
    _write_u64(arg_types_addr, _pin_str_array(types))
    _write_u64(arg_descs_addr, _pin_str_array(descs))
    _write_u64(key_var_num_args_addr, _pin_str(""))
    _write_u64(return_type_addr, _pin_str("NDArray-or-Symbol"))


@capi
def sym_create_atomic_symbol(creator_hid, num_param, keys_addr, vals_addr,
                             out_addr):
    op = _obj(creator_hid)
    attrs = _parse_params(num_param, keys_addr, vals_addr)
    _write_u64(out_addr, _new_handle(_AtomicSymbol(op, attrs)))


@capi
def sym_create_variable(name_addr, out_addr):
    v = _sym_mod().Variable(_read_str(name_addr))
    _write_u64(out_addr, _new_handle(v))


@capi
def sym_create_group(num, symbols_addr, out_addr):
    syms = [_obj(h) for h in _read_u64_array(symbols_addr, num)]
    _write_u64(out_addr, _new_handle(_sym_mod().Group(syms)))


@capi
def sym_create_from_file(fname_addr, out_addr):
    _write_u64(out_addr,
               _new_handle(_sym_mod().load(_read_str(fname_addr))))


@capi
def sym_create_from_json(json_addr, out_addr):
    _write_u64(out_addr,
               _new_handle(_sym_mod().load_json(_read_str(json_addr))))


@capi
def sym_save_to_file(hid, fname_addr):
    _obj(hid).save(_read_str(fname_addr))


@capi
def sym_save_to_json(hid, out_addr):
    _write_u64(out_addr, _pin_str(_obj(hid).tojson()))


@capi
def sym_free(hid):
    _free_handle(hid)


@capi
def sym_copy(hid, out_addr):
    import copy

    _write_u64(out_addr, _new_handle(copy.deepcopy(_obj(hid))))


@capi
def sym_print(hid, out_addr):
    _write_u64(out_addr, _pin_str(repr(_obj(hid))))


@capi
def sym_get_name(hid, out_addr, success_addr):
    name = _obj(hid).name
    if name is None:
        _write_i32(success_addr, 0)
    else:
        _write_u64(out_addr, _pin_str(name))
        _write_i32(success_addr, 1)


@capi
def sym_get_attr(hid, key_addr, out_addr, success_addr):
    val = _obj(hid).attr(_read_str(key_addr))
    if val is None:
        _write_i32(success_addr, 0)
    else:
        _write_u64(out_addr, _pin_str(str(val)))
        _write_i32(success_addr, 1)


@capi
def sym_set_attr(hid, key_addr, val_addr):
    _obj(hid)._set_attr(**{_read_str(key_addr): _read_str(val_addr)})


@capi
def sym_list_attr(hid, shallow, out_size_addr, out_addr):
    s = _obj(hid)
    if shallow:
        attrs = dict(s.list_attr())
    else:
        # deep walk: node-name-prefixed "node$key" pairs (reference
        # MXSymbolListAttr recursive format)
        attrs = {}
        for node, node_attrs in s.attr_dict().items():
            for k, v in node_attrs.items():
                attrs["%s$%s" % (node, k)] = v
    flat = []
    for k in sorted(attrs):
        flat += [k, str(attrs[k])]
    _write_u32(out_size_addr, len(flat) // 2)
    _write_u64(out_addr, _pin_str_array(flat))


def _write_str_list(strs, out_size_addr, out_addr):
    _write_u32(out_size_addr, len(strs))
    _write_u64(out_addr, _pin_str_array(strs))


@capi
def sym_list_arguments(hid, out_size_addr, out_addr):
    _write_str_list(_obj(hid).list_arguments(), out_size_addr, out_addr)


@capi
def sym_list_outputs(hid, out_size_addr, out_addr):
    _write_str_list(_obj(hid).list_outputs(), out_size_addr, out_addr)


@capi
def sym_list_auxiliary_states(hid, out_size_addr, out_addr):
    _write_str_list(_obj(hid).list_auxiliary_states(), out_size_addr,
                    out_addr)


@capi
def sym_get_num_outputs(hid, out_addr):
    _write_u32(out_addr, len(_obj(hid).list_outputs()))


@capi
def sym_get_internals(hid, out_addr):
    _write_u64(out_addr, _new_handle(_obj(hid).get_internals()))


@capi
def sym_get_children(hid, out_addr):
    c = _obj(hid).get_children()
    _write_u64(out_addr, _new_handle(c) if c is not None else 0)


@capi
def sym_get_output(hid, index, out_addr):
    _write_u64(out_addr, _new_handle(_obj(hid)[int(index)]))


@capi
def sym_get_input_symbols(hid, out_handles_addr, out_size_addr):
    s = _obj(hid)
    names = s.list_inputs()
    hids = [_new_handle(_sym_mod().Variable(n)) for n in names]
    _write_u64(out_handles_addr, _pin_array(ctypes.c_uint64, hids))
    _write_u32(out_size_addr, len(hids))


@capi
def sym_compose(hid, name_addr, num_args, keys_addr, args_addr):
    target = _handles[int(hid)]
    name = _read_str(name_addr)
    keys = _read_str_array(keys_addr, num_args) if keys_addr else None
    args = [_obj(h) for h in _read_u64_array(args_addr, num_args)]
    if isinstance(target, _AtomicSymbol):
        fn = getattr(_sym_mod(), target.op.name, None)
        if fn is None and target.op.name.startswith("_"):
            fn = getattr(_sym_mod(), target.op.name.lstrip("_"), None)
        if fn is None:
            raise ValueError("Compose: op %s has no symbol constructor"
                             % target.op.name)
        kwargs = dict(target.attrs)
        if name:
            kwargs["name"] = name
        if keys:
            kwargs.update(dict(zip(keys, args)))
            composed = fn(**kwargs)
        else:
            composed = fn(*args, **kwargs)
        _handles[int(hid)] = composed  # compose mutates, per reference
    else:
        # _compose is pure input substitution; node names were fixed at
        # creation, so the name arg only applies to the atomic path.
        if keys:
            target._compose(**dict(zip(keys, args)))
        else:
            target._compose(*args)


def _provided_shapes(num_args, keys_addr, ind_ptr_addr, shape_data_addr,
                     arg_names):
    ind = _read_u32_array(ind_ptr_addr, num_args + 1)
    flat = _read_u32_array(shape_data_addr, ind[-1] if ind else 0)
    shapes = [tuple(flat[ind[i]:ind[i + 1]]) for i in range(num_args)]
    if keys_addr:
        keys = _read_str_array(keys_addr, num_args)
        return dict(zip(keys, shapes))
    return dict(zip(arg_names, shapes))


def _pin_shape_group(shapes):
    """Pin one (size, ndim[], data[][]) triple for InferShape results."""
    shapes = [tuple(s) if s is not None else () for s in shapes]
    ndims = [len(s) for s in shapes]
    dim_addrs = [_pin_array(ctypes.c_uint32, list(s)) for s in shapes]
    data = _pin_array(ctypes.c_uint64, dim_addrs)
    return len(shapes), _pin_array(ctypes.c_uint32, ndims), data


@capi
def sym_infer_shape(hid, partial, num_args, keys_addr, ind_ptr_addr,
                    shape_data_addr, in_size_addr, in_ndim_addr, in_data_addr,
                    out_size_addr, out_ndim_addr, out_data_addr,
                    aux_size_addr, aux_ndim_addr, aux_data_addr,
                    complete_addr):
    s = _obj(hid)
    kwargs = _provided_shapes(num_args, keys_addr, ind_ptr_addr,
                              shape_data_addr, s.list_arguments())
    kwargs = {k: v for k, v in kwargs.items() if v}
    if partial:
        arg_shapes, out_shapes, aux_shapes = s.infer_shape_partial(**kwargs)
    else:
        arg_shapes, out_shapes, aux_shapes = s.infer_shape(**kwargs)
    groups = []
    for shapes, size_a, ndim_a, data_a in (
            (arg_shapes, in_size_addr, in_ndim_addr, in_data_addr),
            (out_shapes, out_size_addr, out_ndim_addr, out_data_addr),
            (aux_shapes, aux_size_addr, aux_ndim_addr, aux_data_addr)):
        shapes = shapes or []
        n, ndim_ptr, data_ptr = _pin_shape_group(shapes)
        _write_u32(size_a, n)
        _write_u64(ndim_a, ndim_ptr)
        _write_u64(data_a, data_ptr)
        groups.append(shapes)
    complete = all(s is not None and all(d > 0 for d in s)
                   for grp in groups for s in grp)
    _write_i32(complete_addr, int(complete))


@capi
def sym_infer_type(hid, num_args, keys_addr, types_addr, in_size_addr,
                   in_data_addr, out_size_addr, out_data_addr, aux_size_addr,
                   aux_data_addr, complete_addr):
    s = _obj(hid)
    codes = _read_i32_array(types_addr, num_args)
    if keys_addr:
        keys = _read_str_array(keys_addr, num_args)
    else:
        keys = s.list_arguments()[:num_args]
    kwargs = {k: _np_dtype_of_code(c) for k, c in zip(keys, codes)
              if c >= 0}
    arg_types, out_types, aux_types = s.infer_type(**kwargs)

    def codes_of(types):
        return [(_code_of_np_dtype(t) if t is not None else -1)
                for t in (types or [])]

    for types, size_a, data_a in ((arg_types, in_size_addr, in_data_addr),
                                  (out_types, out_size_addr, out_data_addr),
                                  (aux_types, aux_size_addr, aux_data_addr)):
        cs = codes_of(types)
        _write_u32(size_a, len(cs))
        _write_u64(data_a, _pin_array(ctypes.c_int32, cs))
    complete = all(t is not None for t in (arg_types or [])) and \
        all(t is not None for t in (out_types or []))
    _write_i32(complete_addr, int(complete))


_qsym_meta: dict[int, tuple] = {}


@capi
def quantize_symbol(hid, out_addr, num_excluded, excluded_addr, qdtype_addr):
    from .contrib import quantization as q

    sym = _obj(hid)
    excluded = _read_str_array(excluded_addr, num_excluded)
    qdtype = _read_str(qdtype_addr) or "int8"
    qsym = q.quantize_graph(sym, excluded_sym_names=excluded,
                            quantized_dtype=qdtype)
    hid_out = _new_handle(qsym)
    _qsym_meta[hid_out] = (sym, tuple(excluded), qdtype)
    _write_u64(out_addr, hid_out)


@capi
def set_calib_table_to_quantized_symbol(qsym_hid, num_layers, names_addr,
                                        low_addr, high_addr, out_addr):
    from .contrib import quantization as q

    meta = _qsym_meta.get(int(qsym_hid))
    if meta is None:
        raise ValueError("SetCalibTable: handle was not produced by "
                         "QuantizeSymbol")
    sym, excluded, qdtype = meta
    names = _read_str_array(names_addr, num_layers)
    lows = _read_f32_array(low_addr, num_layers)
    highs = _read_f32_array(high_addr, num_layers)
    th_dict = {n: (lo, hi) for n, lo, hi in zip(names, lows, highs)}
    qsym = q.quantize_graph(sym, excluded_sym_names=list(excluded),
                            th_dict=th_dict, quantized_dtype=qdtype)
    _write_u64(out_addr, _new_handle(qsym))


@capi
def gen_backend_subgraph(hid, backend_addr, out_addr):
    from .symbol.subgraph import partition_graph

    part = partition_graph(_obj(hid), _read_str(backend_addr))
    _write_u64(out_addr, _new_handle(part))


# =============================================================== executor --
_exec_syms: dict[int, object] = {}


def _executor_arrays(executor):
    args = [_new_handle(a) for a in executor.arg_arrays]
    grads = [(_new_handle(g) if g is not None else 0)
             for g in executor.grad_arrays]
    auxs = [_new_handle(a) for a in executor.aux_arrays]
    return args, grads, auxs


@capi
def exec_free(hid):
    _exec_syms.pop(int(hid), None)
    _free_handle(hid)


@capi
def exec_print(hid, out_addr):
    _write_u64(out_addr, _pin_str(_obj(hid).debug_str()))


@capi
def exec_forward(hid, is_train):
    _obj(hid).forward(is_train=bool(is_train))


@capi
def exec_backward(hid, length, head_grads_addr, is_train):
    ids = _read_u64_array(head_grads_addr, length)
    ograds = [_obj(h) for h in ids] if ids else None
    _obj(hid).backward(out_grads=ograds, is_train=bool(is_train))


@capi
def exec_outputs(hid, out_size_addr, out_addr):
    outs = [_new_handle(o) for o in _obj(hid).outputs]
    _write_u32(out_size_addr, len(outs))
    _write_u64(out_addr, _pin_array(ctypes.c_uint64, outs))


@capi
def exec_bind(sym_hid, dev_type, dev_id, length, in_args_addr, grads_addr,
              reqs_addr, aux_len, aux_addr, shared_exec, out_addr):
    del shared_exec  # binding is jit-cached; sharing is automatic
    sym = _obj(sym_hid)
    ctx = _ctx(dev_type, dev_id)
    args = [_obj(h) for h in _read_u64_array(in_args_addr, length)]
    grad_ids = _read_u64_array(grads_addr, length)
    names = sym.list_arguments()
    grads = {n: _obj(h) for n, h in zip(names, grad_ids) if h}
    reqs = [_GRAD_REQ_NAMES[c] for c in _read_u32_array(reqs_addr, length)] \
        if reqs_addr else ["write"] * length
    aux = [_obj(h) for h in _read_u64_array(aux_addr, aux_len)]
    executor = sym.bind(ctx, args, args_grad=grads,
                        grad_req=dict(zip(names, reqs)), aux_states=aux)
    hid = _new_handle(executor)
    _exec_syms[hid] = sym
    _write_u64(out_addr, hid)


@capi
def exec_simple_bind(sym_hid, dev_type, dev_id, num_reqs, req_names_addr,
                     req_types_addr, num_shapes, shape_names_addr,
                     shape_data_addr, shape_idx_addr, num_dtypes,
                     dtype_names_addr, dtypes_addr, num_stypes,
                     stype_names_addr, stypes_addr, num_shared_arg_names,
                     shared_arg_names_addr, shared_buffer_len_addr,
                     shared_buffer_names_addr, shared_buffer_handles_addr,
                     upd_shared_buffer_names_addr,
                     upd_shared_buffer_handles_addr, num_in_args_addr,
                     in_args_addr, arg_grads_addr, num_aux_addr, aux_addr,
                     shared_exec, out_addr):
    del num_shared_arg_names, shared_arg_names_addr, shared_exec
    sym = _obj(sym_hid)
    ctx = _ctx(dev_type, dev_id)
    # provided shapes: CSR packing over names
    idx = _read_u32_array(shape_idx_addr, num_shapes + 1)
    flat = _read_u32_array(shape_data_addr, idx[-1] if idx else 0)
    shape_names = _read_str_array(shape_names_addr, num_shapes)
    kwargs = {n: tuple(flat[idx[i]:idx[i + 1]])
              for i, n in enumerate(shape_names)}
    type_dict = {n: _np_dtype_of_code(c)
                 for n, c in zip(_read_str_array(dtype_names_addr,
                                                 num_dtypes),
                                 _read_i32_array(dtypes_addr, num_dtypes))}
    stype_dict = {n: {0: "default", 1: "row_sparse", 2: "csr"}[c]
                  for n, c in zip(_read_str_array(stype_names_addr,
                                                  num_stypes),
                                  _read_i32_array(stypes_addr, num_stypes))}
    if num_reqs:
        grad_req = dict(zip(_read_str_array(req_names_addr, num_reqs),
                            _read_str_array(req_types_addr, num_reqs)))
    else:
        grad_req = "write"
    executor = sym.simple_bind(ctx, grad_req=grad_req,
                               type_dict=type_dict or None,
                               stype_dict=stype_dict or None, **kwargs)
    hid = _new_handle(executor)
    _exec_syms[hid] = sym
    args, grads, auxs = _executor_arrays(executor)
    _write_u32(num_in_args_addr, len(args))
    _write_u64(in_args_addr, _pin_array(ctypes.c_uint64, args))
    _write_u64(arg_grads_addr, _pin_array(ctypes.c_uint64, grads))
    _write_u32(num_aux_addr, len(auxs))
    _write_u64(aux_addr, _pin_array(ctypes.c_uint64, auxs))
    # shared buffer passes through unchanged (XLA owns memory reuse)
    if shared_buffer_len_addr:
        n = _read_i32(shared_buffer_len_addr)
        if n > 0:
            _write_u64(upd_shared_buffer_names_addr,
                       int(shared_buffer_names_addr))
            _write_u64(upd_shared_buffer_handles_addr,
                       int(shared_buffer_handles_addr))
    _write_u64(out_addr, hid)


@capi
def exec_reshape(partial_shaping, allow_up_sizing, dev_type, dev_id,
                 num_shapes, shape_names_addr, shape_data_addr,
                 shape_idx_addr, num_in_args_addr, in_args_addr,
                 arg_grads_addr, num_aux_addr, aux_addr, shared_exec_hid,
                 out_addr):
    del dev_type, dev_id
    src = _obj(shared_exec_hid)
    idx = _read_u32_array(shape_idx_addr, num_shapes + 1)
    flat = _read_u32_array(shape_data_addr, idx[-1] if idx else 0)
    names = _read_str_array(shape_names_addr, num_shapes)
    kwargs = {n: tuple(flat[idx[i]:idx[i + 1]]) for i, n in enumerate(names)}
    executor = src.reshape(partial_shaping=bool(partial_shaping),
                           allow_up_sizing=bool(allow_up_sizing), **kwargs)
    hid = _new_handle(executor)
    _exec_syms[hid] = _exec_syms.get(int(shared_exec_hid))
    args, grads, auxs = _executor_arrays(executor)
    _write_u32(num_in_args_addr, len(args))
    _write_u64(in_args_addr, _pin_array(ctypes.c_uint64, args))
    _write_u64(arg_grads_addr, _pin_array(ctypes.c_uint64, grads))
    _write_u32(num_aux_addr, len(auxs))
    _write_u64(aux_addr, _pin_array(ctypes.c_uint64, auxs))
    _write_u64(out_addr, hid)


@capi
def exec_get_optimized_symbol(hid, out_addr):
    sym = _exec_syms.get(int(hid))
    if sym is None:
        sym = _obj(hid)._symbol
    _write_u64(out_addr, _new_handle(sym))


_MonitorCB = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_uint64,
                              ctypes.c_void_p)


@capi
def exec_set_monitor_callback(hid, cb_addr, cb_ctx, monitor_all):
    executor = _obj(hid)
    cfn = _MonitorCB(int(cb_addr))

    def py_cb(name, arr):
        h = _new_handle(arr)
        try:
            nm = name if isinstance(name, bytes) else str(name).encode()
            cfn(nm, h, cb_ctx)
        finally:
            _free_handle(h)

    executor.set_monitor_callback(py_cb, monitor_all=bool(monitor_all))


# ============================================================== cached op --
class _CCachedOp:
    """C-ABI CachedOp: a symbol plus a shape/dtype-keyed executor cache
    (reference: src/imperative/cached_op.cc; here the jit cache under
    simple_bind already gives the op-graph reuse)."""

    def __init__(self, sym, flags):
        self.sym = sym
        self.flags = flags
        self._cache = {}

    def invoke(self, inputs):
        names = self.sym.list_arguments()
        if len(inputs) != len(names):
            raise ValueError("InvokeCachedOp: expected %d inputs (%s), got "
                             "%d" % (len(names), names, len(inputs)))
        key = tuple((tuple(a.shape), str(a.dtype)) for a in inputs)
        executor = self._cache.get(key)
        if executor is None:
            shapes = {n: tuple(a.shape) for n, a in zip(names, inputs)}
            types = {n: a.dtype for n, a in zip(names, inputs)}
            executor = self.sym.simple_bind(inputs[0].context,
                                            grad_req="null",
                                            type_dict=types, **shapes)
            self._cache[key] = executor
        return executor.forward(is_train=False,
                                **dict(zip(names, inputs)))


@capi
def create_cached_op(sym_hid, num_flags, keys_addr, vals_addr, out_addr):
    flags = _parse_params(num_flags, keys_addr, vals_addr)
    _write_u64(out_addr, _new_handle(_CCachedOp(_obj(sym_hid), flags)))


@capi
def free_cached_op(hid):
    _free_handle(hid)


@capi
def invoke_cached_op(hid, num_inputs, inputs_addr, num_outputs_addr,
                     outputs_addr, out_stypes_addr):
    op = _obj(hid)
    inputs = [_obj(h) for h in _read_u64_array(inputs_addr, num_inputs)]
    outs = op.invoke(inputs)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    hids = [_new_handle(o) for o in outs]
    _write_i32(num_outputs_addr, len(hids))
    _write_u64(outputs_addr, _pin_array(ctypes.c_uint64, hids))
    if out_stypes_addr:
        codes = [_STYPE_CODES.get(getattr(o, "stype", "default"), 0)
                 for o in outs]
        _write_u64(out_stypes_addr, _pin_array(ctypes.c_int32, codes))


# ============================================================== data iter --
def _iter_creators():
    from . import io as _io
    from .image import ImageIter
    from .image_detection import ImageDetIter

    return [_io.MNISTIter, _io.CSVIter, _io.LibSVMIter, _io.ImageRecordIter,
            ImageIter, ImageDetIter]


_iter_creator_handles: list[int] = []


@capi
def list_data_iters(out_size_addr, out_array_addr):
    if not _iter_creator_handles:
        _iter_creator_handles.extend(_new_handle(c)
                                     for c in _iter_creators())
    _write_u32(out_size_addr, len(_iter_creator_handles))
    _write_u64(out_array_addr,
               _pin_array(ctypes.c_uint64, _iter_creator_handles))


@capi
def data_iter_get_iter_info(creator_hid, name_addr, desc_addr, num_args_addr,
                            arg_names_addr, arg_types_addr, arg_descs_addr):
    import inspect

    cls = _obj(creator_hid)
    sig = inspect.signature(cls.__init__)
    params = [p for p in sig.parameters.values()
              if p.name not in ("self", "args", "kwargs")]
    names = [p.name for p in params]
    types = [("required" if p.default is inspect.Parameter.empty
              else "optional, default=%r" % (p.default,)) for p in params]
    _write_u64(name_addr, _pin_str(cls.__name__))
    _write_u64(desc_addr,
               _pin_str((cls.__doc__ or "").strip().split("\n")[0]))
    _write_u32(num_args_addr, len(names))
    _write_u64(arg_names_addr, _pin_str_array(names))
    _write_u64(arg_types_addr, _pin_str_array(types))
    _write_u64(arg_descs_addr, _pin_str_array(["" for _ in names]))


class _IterState:
    def __init__(self, it):
        self.it = it
        self.batch = None


@capi
def data_iter_create(creator_hid, num_param, keys_addr, vals_addr, out_addr):
    cls = _obj(creator_hid)
    kwargs = _parse_params(num_param, keys_addr, vals_addr)
    _write_u64(out_addr, _new_handle(_IterState(cls(**kwargs))))


@capi
def data_iter_free(hid):
    _free_handle(hid)


@capi
def data_iter_next(hid, out_addr):
    st = _obj(hid)
    try:
        st.batch = st.it.next()
        _write_i32(out_addr, 1)
    except StopIteration:
        st.batch = None
        _write_i32(out_addr, 0)


@capi
def data_iter_before_first(hid):
    st = _obj(hid)
    st.it.reset()
    st.batch = None


def _batch_of(hid):
    st = _obj(hid)
    if st.batch is None:
        raise ValueError("DataIter: call Next before reading the batch")
    return st.batch


@capi
def data_iter_get_data(hid, out_addr):
    _write_u64(out_addr, _new_handle(_batch_of(hid).data[0]))


@capi
def data_iter_get_label(hid, out_addr):
    _write_u64(out_addr, _new_handle(_batch_of(hid).label[0]))


@capi
def data_iter_get_index(hid, out_index_addr, out_size_addr):
    idx = _batch_of(hid).index
    vals = [int(v) for v in (idx if idx is not None else [])]
    _write_u64(out_index_addr, _pin_array(ctypes.c_uint64, vals))
    _write(ctypes.c_uint64, out_size_addr, len(vals))


@capi
def data_iter_get_pad_num(hid, out_addr):
    _write_i32(out_addr, int(_batch_of(hid).pad or 0))


# ================================================================ kvstore --
def _kv_mod():
    from . import kvstore as _kv

    return _kv


@capi
def kv_create(type_addr, out_addr):
    kv = _kv_mod().create(_read_str(type_addr) or "local")
    _write_u64(out_addr, _new_handle(kv))


@capi
def kv_free(hid):
    _free_handle(hid)


def _kv_keys(num, keys_addr, str_keys):
    if str_keys:
        return _read_str_array(keys_addr, num)
    return _read_i32_array(keys_addr, num)


@capi
def kv_init(hid, num, keys_addr, str_keys, vals_addr):
    kv = _obj(hid)
    keys = _kv_keys(num, keys_addr, str_keys)
    vals = [_obj(h) for h in _read_u64_array(vals_addr, num)]
    kv.init(keys if len(keys) > 1 else keys[0],
            vals if len(vals) > 1 else vals[0])


@capi
def kv_push(hid, num, keys_addr, str_keys, vals_addr, priority):
    kv = _obj(hid)
    keys = _kv_keys(num, keys_addr, str_keys)
    vals = [_obj(h) for h in _read_u64_array(vals_addr, num)]
    kv.push(keys if len(keys) > 1 else keys[0],
            vals if len(vals) > 1 else vals[0], priority=priority)


@capi
def kv_pull(hid, num, keys_addr, str_keys, vals_addr, priority,
            ignore_sparse):
    kv = _obj(hid)
    keys = _kv_keys(num, keys_addr, str_keys)
    outs = [_obj(h) for h in _read_u64_array(vals_addr, num)]
    kv.pull(keys if len(keys) > 1 else keys[0],
            out=outs if len(outs) > 1 else outs[0], priority=priority,
            ignore_sparse=bool(ignore_sparse))


@capi
def kv_pull_row_sparse(hid, num, keys_addr, str_keys, vals_addr,
                       row_ids_addr, priority):
    kv = _obj(hid)
    keys = _kv_keys(num, keys_addr, str_keys)
    outs = [_obj(h) for h in _read_u64_array(vals_addr, num)]
    row_ids = [_obj(h) for h in _read_u64_array(row_ids_addr, num)]
    kv.row_sparse_pull(keys if len(keys) > 1 else keys[0],
                       out=outs if len(outs) > 1 else outs[0],
                       priority=priority,
                       row_ids=row_ids if len(row_ids) > 1 else row_ids[0])


_KVUpdater = ctypes.CFUNCTYPE(None, ctypes.c_int32, ctypes.c_uint64,
                              ctypes.c_uint64, ctypes.c_void_p)
_KVStrUpdater = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint64, ctypes.c_void_p)


@capi
def kv_set_updater(hid, updater_addr, str_updater_addr, updater_ctx):
    kv = _obj(hid)
    int_fn = _KVUpdater(int(updater_addr)) if updater_addr else None
    str_fn = (_KVStrUpdater(int(str_updater_addr))
              if str_updater_addr else None)

    def py_updater(key, recv, local):
        hr, hl = _new_handle(recv), _new_handle(local)
        try:
            if isinstance(key, str):
                if str_fn is None:
                    raise ValueError("string key %r but no str updater set"
                                     % key)
                str_fn(key.encode(), hr, hl, updater_ctx)
            else:
                if int_fn is None:
                    raise ValueError("int key %r but no int updater set"
                                     % key)
                int_fn(int(key), hr, hl, updater_ctx)
        finally:
            _free_handle(hr)
            _free_handle(hl)

    kv.set_updater(py_updater)


@capi
def kv_get_type(hid, out_addr):
    _write_u64(out_addr, _pin_str(_obj(hid).type))


@capi
def kv_get_rank(hid, out_addr):
    _write_i32(out_addr, int(_obj(hid).rank))


@capi
def kv_get_group_size(hid, out_addr):
    _write_i32(out_addr, int(_obj(hid).num_workers))


@capi
def kv_barrier(hid):
    kv = _obj(hid)
    fn = getattr(kv, "_barrier", None) or getattr(kv, "barrier", None)
    if fn is not None:
        fn()


def _role():
    import os

    return os.environ.get("DMLC_ROLE", "worker")


@capi
def kv_is_worker_node(out_addr):
    _write_i32(out_addr, int(_role() == "worker"))


@capi
def kv_is_server_node(out_addr):
    _write_i32(out_addr, int(_role() == "server"))


@capi
def kv_is_scheduler_node(out_addr):
    _write_i32(out_addr, int(_role() == "scheduler"))


_KVController = ctypes.CFUNCTYPE(None, ctypes.c_int32, ctypes.c_char_p,
                                 ctypes.c_void_p)


@capi
def kv_run_server(hid, controller_addr, controller_ctx):
    if _role() != "server":
        raise RuntimeError("RunServer: DMLC_ROLE is %r, not 'server'"
                           % _role())
    from . import kvstore_server

    del hid
    cfn = _KVController(int(controller_addr)) if controller_addr else None
    controller = None
    if cfn is not None:
        def controller(head, body):
            cfn(int(head), str(body).encode(), controller_ctx)
    kvstore_server.init_server(controller=controller)


@capi
def kv_send_command_to_servers(hid, cmd_id, body_addr):
    _obj(hid)._send_command_to_servers(int(cmd_id), _read_str(body_addr)
                                       or "")


@capi
def kv_set_barrier_before_exit(hid, do_barrier):
    _obj(hid)._barrier_before_exit = bool(do_barrier)


@capi
def kv_get_num_dead_node(hid, node_id, out_addr, timeout_sec):
    del node_id, timeout_sec
    kv = _obj(hid)
    _write_i32(out_addr, int(getattr(kv, "num_dead_nodes", 0)))


@capi
def kv_set_gradient_compression(hid, num, keys_addr, vals_addr):
    kv = _obj(hid)
    kv.set_gradient_compression(_parse_params(num, keys_addr, vals_addr))


@capi
def init_ps_env(num, keys_addr, vals_addr):
    import os

    keys = _read_str_array(keys_addr, num)
    vals = _read_str_array(vals_addr, num)
    os.environ.update(dict(zip(keys, vals)))


# =============================================================== profiler --
def _profiler():
    from . import profiler

    return profiler


@capi
def profiler_set_config(num, keys_addr, vals_addr, kvstore_hid):
    params = _parse_params(num, keys_addr, vals_addr)
    if kvstore_hid:
        _profiler().set_kvstore_handle(_obj(kvstore_hid))
    _profiler().set_config(**params)


@capi
def profiler_set_state(state, profile_process):
    kw = {}
    if profile_process:
        kw["profile_process"] = ("server" if profile_process == 1
                                 else "worker")
    _profiler().set_state("run" if state else "stop", **kw)


@capi
def profiler_dump(finished, profile_process):
    kw = {}
    if profile_process:
        kw["profile_process"] = ("server" if profile_process == 1
                                 else "worker")
    _profiler().dump(finished=bool(finished), **kw)


@capi
def profiler_aggregate_stats_print(out_addr, reset):
    _write_u64(out_addr, _pin_str(_profiler().dumps(reset=bool(reset))))


@capi
def profiler_pause(paused, profile_process):
    kw = {}
    if profile_process:
        kw["profile_process"] = ("server" if profile_process == 1
                                 else "worker")
    if paused:
        _profiler().pause(**kw)
    else:
        _profiler().resume(**kw)


@capi
def profile_create_domain(name_addr, out_addr):
    _write_u64(out_addr,
               _new_handle(_profiler().Domain(_read_str(name_addr))))


@capi
def profile_create_task(domain_hid, name_addr, out_addr):
    _write_u64(out_addr,
               _new_handle(_obj(domain_hid).new_task(_read_str(name_addr))))


@capi
def profile_create_frame(domain_hid, name_addr, out_addr):
    _write_u64(out_addr,
               _new_handle(_obj(domain_hid).new_frame(_read_str(name_addr))))


@capi
def profile_create_event(name_addr, out_addr):
    _write_u64(out_addr,
               _new_handle(_profiler().Event(_read_str(name_addr))))


@capi
def profile_create_counter(domain_hid, name_addr, out_addr):
    _write_u64(out_addr, _new_handle(
        _obj(domain_hid).new_counter(_read_str(name_addr))))


@capi
def profile_destroy_handle(hid):
    _free_handle(hid)


@capi
def profile_duration_start(hid):
    _obj(hid).start()


@capi
def profile_duration_stop(hid):
    _obj(hid).stop()


@capi
def profile_set_counter(hid, value):
    _obj(hid).set_value(int(value))


@capi
def profile_adjust_counter(hid, delta):
    _obj(hid).increment(int(delta))


@capi
def profile_set_marker(domain_hid, name_addr, scope_addr):
    marker = _obj(domain_hid).new_marker(_read_str(name_addr))
    scope = _read_str(scope_addr) or "process"
    mark = getattr(marker, "mark", None)
    if mark is not None:
        mark(scope)
