"""Tensor-parallel partition rules — Megatron-style sharding via GSPMD.

The reference has only manual model parallelism (group2ctx device
placement, graph_executor.cc:1628).  TPU-native model parallelism is
declarative: each parameter gets a ``PartitionSpec`` over the mesh and
XLA inserts the all-reduces.  The rules below implement the canonical
transformer sharding:

- QKV / FFN-in projections: column-parallel (output dim over 'tp') —
  FullyConnected weights are (out_units, in_units), so dim 0;
- attention-out / FFN-out projections: row-parallel (input dim over
  'tp'), whose matmul partial sums GSPMD combines with one psum;
- token embedding and logits head: vocab-sharded over 'tp';
- everything else (norms, biases of row-parallel layers, positions):
  replicated.

A rule is ``(regex, PartitionSpec)``; first match on the parameter name
wins.  ``spec_for`` drops mesh axes of size 1 so the same rules work on
any mesh shape.
"""

from __future__ import annotations

import re

__all__ = ["TRANSFORMER_RULES", "spec_for", "make_param_spec_fn"]


def _P(*spec):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*spec)


def TRANSFORMER_RULES():
    return [
        (r"qkv_weight$", _P("tp", None)),
        (r"qkv_bias$", _P("tp")),
        (r"proj_weight$", _P(None, "tp")),
        (r"ffn1_weight$", _P("tp", None)),
        (r"ffn1_bias$", _P("tp")),
        (r"ffn2_weight$", _P(None, "tp")),
        (r"logits_weight$", _P("tp", None)),
        (r"embed_weight$", _P("tp", None)),
    ]


def spec_for(name, shape, rules=None, mesh=None):
    """PartitionSpec for a parameter by name; replicated if no rule hits.

    Axes missing from the mesh or of size 1 are dropped from the spec,
    and axes whose shard count does not divide the dim are dropped, so
    rules are safe across mesh shapes and odd layer sizes.
    """
    from jax.sharding import PartitionSpec

    rules = TRANSFORMER_RULES() if rules is None else rules
    for pat, spec in rules:
        if re.search(pat, name):
            if mesh is None:
                return spec
            cleaned = []
            for dim, ax in enumerate(spec):
                ok = (ax is not None and ax in mesh.shape
                      and mesh.shape[ax] > 1
                      and dim < len(shape)
                      and shape[dim] % mesh.shape[ax] == 0)
                cleaned.append(ax if ok else None)
            while cleaned and cleaned[-1] is None:
                cleaned.pop()
            return PartitionSpec(*cleaned)
    return PartitionSpec()


def make_param_spec_fn(rules=None, mesh=None):
    """-> fn(param_name, shape) -> PartitionSpec, for GluonTrainStep."""

    def fn(name, shape):
        return spec_for(name, shape, rules=rules, mesh=mesh)

    return fn
