"""Sharded data-parallel training step — the production TPU path.

This replaces the reference's whole gradient-synchronisation machinery
(DataParallelExecutorGroup batch slicing + Comm reduce + KVStore
push/pull, SURVEY.md §3.4) with ONE jitted SPMD step over a mesh:

- batch sharded over 'dp' (NamedSharding)
- params replicated over 'dp', optionally sharded over 'tp'
- loss gradient psum happens implicitly when XLA partitions the step
  (GSPMD inserts the all-reduce on the grad reduction)

``make_train_step`` works with any pure loss_fn(params, batch) — the
gluon Trainer and Module multi-chip paths build theirs from the traced
block/symbol.
"""

from __future__ import annotations

import jax

__all__ = ["make_train_step", "DataParallelStep"]


def make_train_step(loss_fn, optimizer_update, mesh, param_shardings=None,
                    donate_params=True):
    """Build a jitted sharded train step.

    loss_fn(params_pytree, batch_pytree) -> scalar loss
    optimizer_update(params, grads, opt_state) -> (new_params, new_opt_state)

    Returns step(params, opt_state, batch) -> (loss, params, opt_state),
    jitted with batch sharded over 'dp' and params/state sharded per
    ``param_shardings`` (replicated by default).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    batch_shard = NamedSharding(mesh, P("dp"))

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, new_opt_state = optimizer_update(params, grads, opt_state)
        return loss, new_params, new_opt_state

    in_shardings = (param_shardings if param_shardings is not None else repl,
                    repl, batch_shard)
    donate = (0, 1) if donate_params else ()
    return jax.jit(step, in_shardings=in_shardings,
                   donate_argnums=donate)


class DataParallelStep:
    """Convenience wrapper holding mesh + compiled step + device params."""

    def __init__(self, loss_fn, optimizer_update, mesh=None):
        from .mesh import get_default_mesh

        self.mesh = mesh or get_default_mesh()
        self._step = make_train_step(loss_fn, optimizer_update, self.mesh)

    def __call__(self, params, opt_state, batch):
        return self._step(params, opt_state, batch)
