"""Long-context attention: ring (sequence-parallel) and Ulysses (all-to-all).

The reference framework predates long-context training (SURVEY.md §5.7 —
nothing shards the sequence dim).  On TPU, sequence/context parallelism
is first-class here:

- **Ring attention**: Q stays put, K/V shards rotate around the 'sp'
  ring via ``lax.ppermute`` (ICI neighbour exchange).  Each step computes
  block attention against the resident K/V shard and folds it into an
  online-softmax accumulator (out, lse) — the distributed analog of the
  flash-attention inner loop.  Peak memory per chip is O(s_local²)
  scores, so total sequence length scales linearly with ring size.
- **Ulysses / all-to-all**: heads are scattered and sequence gathered
  with ``lax.all_to_all``, full-sequence attention runs locally on
  seq-complete/head-sharded tensors, then the transpose is undone.
  Cheaper when heads ≥ ring size; needs full-sequence activations.

Both are pure jax functions differentiable end-to-end (ppermute /
all_to_all have transfer-transposed gradients), usable inside any jitted
shard_map over a mesh with an 'sp' axis.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30  # finite -inf: keeps the online-softmax combine NaN-free

_SKIP, _FULL, _DIAG = 0, 1, 2


def _block_attention(q, k, v, sm_scale, mode):
    """Attention of local q against one K/V shard.

    q: (b, h, sq, d); k, v: (b, h, sk, d).  mode: traced int32 —
    _SKIP (fully masked), _FULL, or _DIAG (same-shard causal).
    Returns (out, lse) with out normalised within the block and
    lse = log-sum-exp of the scaled scores per query row.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]

    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    # mode-dependent masking kept arithmetic (not lax.switch): under
    # shard_map the skip branch would be unvarying over the mesh axis and
    # fail branch-type unification
    row = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    col = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    masked = (mode == _SKIP) | ((mode == _DIAG) & (col > row))
    s = jnp.where(masked, _NEG, s)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, _NEG)                       # all-masked rows
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    o = o / jnp.where(l == 0.0, 1.0, l)
    lse = (m + jnp.log(jnp.where(l == 0.0, 1.0, l)))[..., 0]
    lse = jnp.where(l[..., 0] == 0.0, _NEG, lse)
    return o, lse


def _combine(out_acc, lse_acc, o_i, lse_i):
    """Fold one block's (normalised out, lse) into the accumulator."""
    m = jnp.maximum(lse_acc, lse_i)
    ea = jnp.exp(lse_acc - m)
    eb = jnp.exp(lse_i - m)
    lse_new = m + jnp.log(ea + eb)
    wa = jnp.exp(lse_acc - lse_new)[..., None]
    wb = jnp.exp(lse_i - lse_new)[..., None]
    return out_acc * wa + o_i * wb, lse_new


def ring_attention(q, k, v, axis_name="sp", causal=False, sm_scale=None):
    """Ring attention over sequence shards.

    Must be called inside shard_map/pjit with `axis_name` a mesh axis;
    q, k, v are the local (batch, heads, seq_local, head_dim) shards,
    sequence-sharded contiguously along the axis.  Returns the local
    output shard.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    # ring: each step the resident K/V shard moves to the next device,
    # so at step t device i holds shard (i - t) mod n
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, t):
        out_acc, lse_acc, kk, vv = carry
        src = (idx - t) % n                        # origin of resident K/V
        if causal:
            mode = jnp.where(src > idx, _SKIP,
                             jnp.where(src == idx, _DIAG, _FULL))
        else:
            mode = jnp.int32(_FULL)
        o_i, lse_i = _block_attention(q, kk, vv, sm_scale, mode)
        out_acc, lse_acc = _combine(out_acc, lse_acc, o_i, lse_i)
        # rotate (skip the final, unused rotation is harmless & keeps
        # the loop body uniform)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (out_acc, lse_acc, kk, vv), None

    b, h, sq, d = q.shape
    # the fresh accumulators must carry the same varying-over-axis type
    # as the rotating K/V shards for scan carry unification
    if hasattr(lax, "pcast"):
        _vary = lambda x: lax.pcast(x, (axis_name,), to="varying")
    elif hasattr(lax, "pvary"):
        _vary = lambda x: lax.pvary(x, (axis_name,))
    else:
        # jax without varying-type annotations (no pcast/pvary, e.g.
        # 0.4.x): every value inside shard_map is already device-varying,
        # so the accumulators unify with the rotating K/V carry as-is
        _vary = lambda x: x
    out0 = _vary(jnp.zeros((b, h, sq, d), jnp.float32))
    lse0 = _vary(jnp.full((b, h, sq), _NEG, jnp.float32))
    (out, _, _, _), _ = lax.scan(step, (out0, lse0, k, v), jnp.arange(n))
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name="sp", causal=False, sm_scale=None,
                      attn_fn=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Local shards (b, h, s_local, d) are transposed to (b, h_local, S, d)
    with two all_to_alls, attention runs on the full sequence locally
    (by default the fused flash/XLA path), and the layout is restored.
    Requires heads % axis_size == 0.
    """
    n = lax.psum(1, axis_name)
    # (b, h, s/n, d) -> split heads, gather seq -> (b, h/n, S, d)
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    if attn_fn is None:
        from ..ops.attention import flash_attention
        attn_fn = functools.partial(flash_attention, causal=causal,
                                    sm_scale=sm_scale)
    out = attn_fn(qh, kh, vh)
    # back: split seq, gather heads
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
