"""Functional sharded training step built from a Gluon block.

This is the flagship TPU training path: the whole train step —
forward, loss, backward, optimizer update, BatchNorm running-stat
update — is ONE jitted SPMD computation over a device mesh.  The
reference splits this across GraphExecutor fwd/bwd + KVStore push/pull
+ python optimizer updates (SURVEY.md §3.1/§3.4); GSPMD inserts the
gradient all-reduce over the 'dp' mesh axis automatically, riding ICI.

Used by bench.py, __graft_entry__.py and the multi-chip Trainer path.
"""

from __future__ import annotations

import numpy as _np

from .. import autograd
from .. import random as _random
from ..gluon.block import staged_call
from ..ndarray import NDArray

__all__ = ["GluonTrainStep", "sgd_momentum_init", "sgd_momentum_update"]


def _pure_loss_builder(block, loss_block, trainable, aux,
                       aux_loss_weight=None):
    """Build loss(train_vals, aux_vals, x, y, key) -> (loss, new_aux).

    aux_loss_weight: when set, ``weight * block.collect_aux_losses()``
    (MoE load-balancing etc.) is added to the task loss INSIDE the
    staged step — the ergonomic channel replacing hand-written loss
    Blocks that stash the net to reach its aux losses."""

    def pure_loss(train_vals, aux_vals, x, y, key):
        override = {p: NDArray(v) for p, v in zip(trainable, train_vals)}
        override.update({p: NDArray(v) for p, v in zip(aux, aux_vals)})

        def fwd(x_nd):
            loss = loss_block(block(x_nd), NDArray(y))
            loss = loss.mean()
            if aux_loss_weight is not None:
                loss = loss + aux_loss_weight * block.collect_aux_losses()
            return loss

        loss, scope = staged_call(fwd, override, key, (NDArray(x),))
        new_aux = tuple(
            scope.aux_updates.get(p, override[p]._data) for p in aux)
        return loss._data, new_aux

    return pure_loss


def sgd_momentum_init(train_vals):
    import jax.numpy as jnp

    return tuple(jnp.zeros_like(v) for v in train_vals)


def sgd_momentum_update(lr, momentum=0.9, wd=0.0):
    """Fused SGD(+momentum, +wd) matching the reference semantics
    (src/operator/optimizer_op.cc sgd_mom_update)."""

    def update(train_vals, grads, states):
        new_vals, new_states = [], []
        for w, g, s in zip(train_vals, grads, states):
            g = g + wd * w
            s = momentum * s + g
            new_vals.append((w - lr * s).astype(w.dtype))
            new_states.append(s)
        return tuple(new_vals), tuple(new_states)

    return update


class GluonTrainStep:
    """Compile a Gluon block + loss + optimizer into one sharded step.

    Parameters live as jax arrays in this object (functional style); call
    ``sync_to_params()`` to write them back into the block's Parameters
    for checkpointing with the normal Gluon API.

    compute_dtype: 'bfloat16' casts activations/weights for the matmul/
    conv path while keeping master weights and the update fp32 — the
    TPU-native analog of the reference's multi-precision SGD
    (mp_sgd_update, src/operator/optimizer_op.cc).
    """

    def __init__(self, block, loss_block, mesh=None, lr=0.1, momentum=0.9,
                 wd=0.0, compute_dtype=None, param_spec_fn=None,
                 data_spec=None, label_spec=None, aux_loss_weight=None):
        import jax
        from jax.sharding import NamedSharding

        from .mesh import (data_parallel_sharding, get_default_mesh,
                           replicated_sharding)

        self.block = block
        self.mesh = mesh or get_default_mesh()
        params = list(block.collect_params().values())
        self.trainable = [p for p in params if p.grad_req != "null"]
        self.aux = [p for p in params if p.grad_req == "null"]
        self.train_vals = tuple(p.data().data_jax for p in self.trainable)
        self.aux_vals = tuple(p.data().data_jax for p in self.aux)
        self.opt_state = sgd_momentum_init(self.train_vals)
        self._update = sgd_momentum_update(lr, momentum, wd)
        self._compute_dtype = compute_dtype
        pure_loss = _pure_loss_builder(block, loss_block, self.trainable,
                                       self.aux,
                                       aux_loss_weight=aux_loss_weight)

        cast = compute_dtype

        def step(train_vals, opt_state, aux_vals, x, y, key):
            def loss_of(tv):
                if cast is not None:
                    tv = tuple(v.astype(cast) if v.dtype == _np.float32 else v
                               for v in tv)
                    x_ = x.astype(cast)
                else:
                    x_ = x
                return pure_loss(tv, aux_vals, x_, y, key)

            (loss, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_vals)
            grads = tuple(g.astype(v.dtype)
                          for g, v in zip(grads, train_vals))
            new_vals, new_state = self._update(train_vals, grads, opt_state)
            return loss, new_vals, new_state, new_aux

        repl = replicated_sharding(self.mesh)
        if param_spec_fn is None:
            tv_shard = aux_shard = repl
        else:
            # per-parameter shardings (tensor parallelism etc.) — the
            # optimizer state mirrors the parameter sharding
            tv_shard = tuple(
                NamedSharding(self.mesh, param_spec_fn(p.name, p.shape))
                for p in self.trainable)
            aux_shard = tuple(
                NamedSharding(self.mesh, param_spec_fn(p.name, p.shape))
                for p in self.aux)
        x_shard = (NamedSharding(self.mesh, data_spec) if data_spec is not None
                   else data_parallel_sharding(self.mesh, 1))
        if label_spec is not None:
            y_shard = NamedSharding(self.mesh, label_spec)
        elif data_spec is not None and len(data_spec):
            # labels are rank-1: shard them along the data spec's batch axis
            from jax.sharding import PartitionSpec as _P
            y_shard = NamedSharding(self.mesh, _P(data_spec[0]))
        elif data_spec is not None:
            y_shard = x_shard  # P(): replicated batch -> replicated labels
        else:
            y_shard = x_shard
        # place the functional state onto its shardings up front: committed
        # single-device arrays cannot be implicitly resharded by jit, and
        # this also avoids a first-step transfer.  jnp.array(copy=True)
        # first: device_put to an equivalent sharding aliases the source
        # buffer, and the first donated step would then delete the Gluon
        # Parameter's own array out from under the user
        import jax.numpy as jnp

        def _put(vals, shard):
            vals = tuple(jnp.array(v, copy=True) for v in vals)
            if isinstance(shard, tuple):
                return tuple(jax.device_put(v, s)
                             for v, s in zip(vals, shard))
            return tuple(jax.device_put(v, shard) for v in vals)

        self.train_vals = _put(self.train_vals, tv_shard)
        self.opt_state = _put(self.opt_state, tv_shard)
        self.aux_vals = _put(self.aux_vals, aux_shard)

        self._step_py = step  # un-jitted; composed by make_chained()
        self._step = jax.jit(
            step,
            in_shardings=(tv_shard, tv_shard, aux_shard, x_shard, y_shard,
                          repl),
            # pin outputs to the input layouts: the functional state must
            # keep its sharding across steps (otherwise the compiler may
            # re-shard e.g. a bias, and step 2's in_shardings reject it)
            out_shardings=(repl, tv_shard, tv_shard, aux_shard),
            donate_argnums=(0, 1, 2),
        )
        # place batch-sharded inputs via these shardings
        self.batch_sharding = x_shard
        self.label_sharding = y_shard
        self._repl = repl

    def make_chained(self, n_steps):
        """Jit n_steps training steps as ONE device computation.

        One host dispatch covers the whole chain (lax.fori_loop carrying
        the functional state), so per-call host/relay overhead is paid
        once per n_steps instead of once per step — the device-only
        timing primitive bench.py's regression gate is built on (the
        same chaining trick as tools/bench_device_latency.py, extended
        to the full fwd+bwd+update+BN-stat step).  The per-iteration RNG
        key is fold_in(key, i), so chained(n) visits the same key
        sequence regardless of chain depth.

        The param/optimizer/aux carry is DONATED into the chain (like
        the single-step path): without donation XLA must keep the
        undonated inputs alive across the whole fori_loop, doubling
        peak param+optimizer memory.  Donation invalidates the input
        buffers, so the final carry is written back into this object's
        state — chained(n) advances training exactly like n ``__call__``
        steps (same fold_in key schedule) and repeat calls keep working.

        Returns fn(x, y, key) -> last_loss.
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        step = self._step_py

        def chained(train_vals, opt_state, aux_vals, x, y, key):
            def body(i, carry):
                tv, os_, av, _ = carry
                loss, tv, os_, av = step(tv, os_, av, x, y,
                                         jax.random.fold_in(key, i))
                # fp32 carry regardless of compute dtype (bf16 steps
                # return a bf16 loss; the carry structure must be fixed)
                return (tv, os_, av, loss.astype(jnp.float32))

            init = (train_vals, opt_state, aux_vals,
                    jnp.zeros((), jnp.float32))
            tv, os_, av, loss = lax.fori_loop(0, n_steps, body, init)
            return loss, tv, os_, av

        jitted = jax.jit(chained, donate_argnums=(0, 1, 2))

        def run(x, y, key):
            loss, self.train_vals, self.opt_state, self.aux_vals = jitted(
                self.train_vals, self.opt_state, self.aux_vals, x, y, key)
            return loss

        run._jitted = jitted  # donation introspection (tests)
        return run

    def put_batch(self, x, y):
        """Place a host batch onto the mesh with the dp sharding."""
        import jax

        return (jax.device_put(_np.asarray(x), self.batch_sharding),
                jax.device_put(_np.asarray(y), self.label_sharding))

    def __call__(self, x, y):
        """One training step on device arrays/numpy; returns loss (async)."""
        import jax

        if not isinstance(x, jax.Array):
            x, y = self.put_batch(x, y)
        key = _random.next_key()
        loss, self.train_vals, self.opt_state, self.aux_vals = self._step(
            self.train_vals, self.opt_state, self.aux_vals, x, y, key)
        return loss

    def sync_to_params(self):
        """Write functional values back into the Gluon Parameters.

        Values are gathered off the mesh first: the Parameters feed the
        normal eager API afterwards, and a mesh-committed array mixed
        with default-device eager operands is a placement error on
        multi-device hosts."""
        import jax.numpy as jnp

        for p, v in zip(self.trainable, self.train_vals):
            host = jnp.asarray(_np.asarray(v))
            for d in p._data:
                d._assign(host)
        for p, v in zip(self.aux, self.aux_vals):
            host = jnp.asarray(_np.asarray(v))
            for d in p._data:
                d._assign(host)
