"""Functional sharded training step built from a Gluon block.

This is the flagship TPU training path: the whole train step —
forward, loss, backward, optimizer update, BatchNorm running-stat
update — is ONE jitted SPMD computation over a device mesh.  The
reference splits this across GraphExecutor fwd/bwd + KVStore push/pull
+ python optimizer updates (SURVEY.md §3.1/§3.4); GSPMD inserts the
gradient all-reduce over the 'dp' mesh axis automatically, riding ICI.

ZeRO weight-update sharding (``zero=True`` / ``MXNET_TPU_ZERO=1``,
Xu et al. arXiv:2004.13336): instead of every device holding the full
replicated parameters + optimizer state, each parameter is flattened,
padded to a multiple of the 'dp' axis size n, and laid out as 1-D
shards — each device owns exactly 1/n of every parameter and of every
optimizer-state leaf (state is *born* on that layout, never
materialized replicated).  Inside the one donated program the flat
shards are constrained to replicated for the forward (GSPMD emits the
param all-gather, overlapped with forward compute), the backward's
summed gradients are constrained back to the 1/n layout (the
reduce-scatter; on some backends GSPMD expresses it as
all-reduce + slice — semantically identical), and the optimizer update
runs elementwise on the shards.  The math is unchanged — elementwise
updates commute with sharding — so the step is bit-exact vs the
unsharded dp step.  Docs: docs/ZERO.md.

``optimizer=`` accepts any ``compiled_step_safe`` Optimizer (SGD, NAG,
Signum, Adam, Adamax, FTML, Ftrl, RMSProp, AdaGrad, AdaDelta): the
real fused-kernel update is traced into the step, with per-step
scalars (scheduler lr, bias corrections, t) refilled host-side each
call — the compiled_step.py protocol.  The default stays the fused
sgd-momentum closure.

Used by bench.py, __graft_entry__.py and the multi-chip Trainer path.
"""

from __future__ import annotations

import os

import numpy as _np

from .. import autograd
from .. import health as _health
from .. import random as _random
from .. import runtime_stats as _rts
from .. import xray as _xray
from ..base import MXNetError
from ..gluon.block import staged_call
from ..ndarray import NDArray

__all__ = ["GluonTrainStep", "GluonStep", "sgd_momentum_init",
           "sgd_momentum_update", "zero_env_enabled"]


def zero_env_enabled():
    """True when ``MXNET_TPU_ZERO=1`` asks training wiring to run the
    ZeRO weight-update-sharded step (docs/ZERO.md)."""
    return os.environ.get("MXNET_TPU_ZERO") == "1"


def _padded_size(size, n):
    """``size`` rounded up to a multiple of ``n`` — the flat-shard
    granularity (each of the n devices owns padded/n elements)."""
    return -(-size // n) * n


def _pure_loss_builder(block, loss_block, trainable, aux,
                       aux_loss_weight=None):
    """Build loss(train_vals, aux_vals, x, y, key) -> (loss, new_aux).

    aux_loss_weight: when set, ``weight * block.collect_aux_losses()``
    (MoE load-balancing etc.) is added to the task loss INSIDE the
    staged step — the ergonomic channel replacing hand-written loss
    Blocks that stash the net to reach its aux losses."""

    def pure_loss(train_vals, aux_vals, x, y, key):
        override = {p: NDArray(v) for p, v in zip(trainable, train_vals)}
        override.update({p: NDArray(v) for p, v in zip(aux, aux_vals)})

        def fwd(x_nd):
            out = block(x_nd)
            with _xray.scope(_xray.REGION_LOSS):
                loss = loss_block(out, NDArray(y))
                loss = loss.mean()
                if aux_loss_weight is not None:
                    loss = loss \
                        + aux_loss_weight * block.collect_aux_losses()
            return loss

        loss, scope = staged_call(fwd, override, key, (NDArray(x),))
        new_aux = tuple(
            scope.aux_updates.get(p, override[p]._data) for p in aux)
        return loss._data, new_aux

    return pure_loss


def sgd_momentum_init(train_vals):
    import jax.numpy as jnp

    return tuple(jnp.zeros_like(v) for v in train_vals)


def sgd_momentum_update(lr, momentum=0.9, wd=0.0):
    """Fused SGD(+momentum, +wd) matching the reference semantics
    (src/operator/optimizer_op.cc sgd_mom_update)."""

    def update(train_vals, grads, states):
        new_vals, new_states = [], []
        for w, g, s in zip(train_vals, grads, states):
            g = g + wd * w
            s = momentum * s + g
            new_vals.append((w - lr * s).astype(w.dtype))
            new_states.append(s)
        return tuple(new_vals), tuple(new_states)

    return update


def _global_grad_norm(grads):
    """Fused global grad L2 norm over RAVELED f32 views — the same
    reduction shape on the dp and ZeRO paths (full vs flat-padded
    grads; the pads are exact zeros), so the two paths' health
    trajectories agree bit for bit."""
    import jax.numpy as jnp

    if not grads:
        return jnp.zeros((), jnp.float32)
    total = None
    for g in grads:
        s = jnp.sum(jnp.square(jnp.ravel(g).astype(jnp.float32)))
        total = s if total is None else total + s
    return jnp.sqrt(total)


class _OptimizerUpdate:
    """The real fused-kernel ``Optimizer`` traced into the functional
    step — compiled_step.py's updater-tracing idiom, functional-state
    edition.

    State trees are discovered from 1-element probe weights, never a
    full-size replicated materialization: that is what lets the ZeRO
    path allocate the real leaves directly onto their 1/n shard layout
    (state sharded from step 0).  Probe leaves must be zero-initialized
    — true for every compiled-step-safe optimizer; anything else would
    need a replicated materialization first and raises instead.
    Per-step scalars (scheduler lr, Adam bias correction, ``t``) are
    recomputed host-side each step by :meth:`host_scalars` and enter
    the jitted program as traced arguments via ``scalar_feed``, so
    schedules never recompile and eager vs functional numerics agree
    to the bit.
    """

    def __init__(self, optimizer, dtypes):
        import jax.numpy as jnp

        from ..compiled_step import _state_leaves

        if not getattr(optimizer, "compiled_step_safe", False):
            raise MXNetError(
                "GluonTrainStep(optimizer=...): %s is not compiled-step "
                "safe (host syncs, cross-step host recurrences, or raw "
                "host-scalar math in update()) — see compiled_step.py "
                "for the supported set" % type(optimizer).__name__)
        self.opt = optimizer
        self.templates = []        # per-index probe state tree
        self.leaf_dtypes = []      # per-index [leaf dtype, ...]
        for i, dt in enumerate(dtypes):
            probe = optimizer.create_state(i, NDArray(jnp.zeros((1,), dt)))
            leaves = []
            _state_leaves(probe, leaves)
            for nd in leaves:
                if float(_np.asarray(nd._data).sum()) != 0.0:
                    raise MXNetError(
                        "GluonTrainStep: %s state for parameter %d is "
                        "not zero-initialized — it cannot be allocated "
                        "directly onto a shard layout"
                        % (type(optimizer).__name__, i))
            self.templates.append(probe)
            self.leaf_dtypes.append([nd._data.dtype for nd in leaves])
        self.slots = [(i, name) for i in range(len(dtypes))
                      for name in sorted(optimizer.step_scalars(i))]

    def init_state(self, alloc):
        """Flat state-leaf tuple via ``alloc(param_index, leaf_dtype)``
        — the caller chooses placement (ZeRO passes jitted zeros with
        sharded out_shardings, so leaves are born 1/n per device)."""
        return tuple(alloc(i, dt)
                     for i, dts in enumerate(self.leaf_dtypes)
                     for dt in dts)

    def host_scalars(self):
        """Advance the host step counters and refill every per-step
        scalar slot — one float per (index, name) — for the next call."""
        opt = self.opt
        table = {}
        for i in range(len(self.templates)):
            opt._update_count(i)
            table[i] = opt.step_scalars(i)
        return tuple(float(table[i][name]) for i, name in self.slots)

    def apply(self, train_vals, grads, state_vals, scalars):
        """Traced: run the real ``update()`` on NDArray views of the
        traced values; returns (new train values, new state leaves)."""
        from ..compiled_step import _rebuild_state, _state_leaves
        from ..optimizer import optimizer as _optmod

        it = iter(state_vals)
        traced = [_rebuild_state(t, it) for t in self.templates]
        feed = {(i, name): scalars[k]
                for k, (i, name) in enumerate(self.slots)}
        new_vals = []
        with _optmod.scalar_feed(feed):
            for j, (w, g) in enumerate(zip(train_vals, grads)):
                w_nd, g_nd = NDArray(w), NDArray(g)
                self.opt.update(j, w_nd, g_nd, traced[j])
                new_vals.append(w_nd._data)
        new_state = []
        for t in traced:
            leaves = []
            _state_leaves(t, leaves)
            new_state.extend(nd._data for nd in leaves)
        return tuple(new_vals), tuple(new_state)


def _put(vals, shard):
    """Place functional values onto their shardings up front: committed
    single-device arrays cannot be implicitly resharded by jit, and
    this also avoids a first-step transfer.  jnp.array(copy=True)
    first: device_put to an equivalent sharding aliases the source
    buffer, and the first donated step would then delete the Gluon
    Parameter's own array out from under the user."""
    import jax
    import jax.numpy as jnp

    vals = tuple(jnp.array(v, copy=True) for v in vals)
    if isinstance(shard, tuple):
        return tuple(jax.device_put(v, s) for v, s in zip(vals, shard))
    return tuple(jax.device_put(v, shard) for v in vals)


class GluonTrainStep:
    """Compile a Gluon block + loss + optimizer into one sharded step.

    Parameters live as jax arrays in this object (functional style); call
    ``sync_to_params()`` to write them back into the block's Parameters
    for checkpointing with the normal Gluon API.

    compute_dtype: 'bfloat16' casts activations/weights for the matmul/
    conv path while keeping master weights and the update fp32 — the
    TPU-native analog of the reference's multi-precision SGD
    (mp_sgd_update, src/operator/optimizer_op.cc).

    zero: weight-update sharding (module docstring) — params and
    optimizer state live as flat 1/n 'dp' shards; default from
    ``MXNET_TPU_ZERO``.  ``self.zero_layout`` describes the layout and
    the per-step collective bytes (also fed into the
    ``zero_allgather_bytes`` / ``zero_reduce_bytes`` runtime counters).

    optimizer: a ``compiled_step_safe`` Optimizer instance traced into
    the step (the real fused-kernel update); None keeps the fused
    sgd-momentum closure built from ``lr/momentum/wd``.
    """

    def __init__(self, block, loss_block, mesh=None, lr=0.1, momentum=0.9,
                 wd=0.0, compute_dtype=None, param_spec_fn=None,
                 data_spec=None, label_spec=None, aux_loss_weight=None,
                 zero=None, optimizer=None):
        import jax
        from jax.sharding import NamedSharding

        from .mesh import (data_parallel_sharding, get_default_mesh,
                           replicated_sharding)

        self.block = block
        self.mesh = mesh or get_default_mesh()
        self._zero = zero_env_enabled() if zero is None else bool(zero)
        if self._zero and param_spec_fn is not None:
            raise MXNetError(
                "GluonTrainStep: zero=True owns the parameter layout "
                "(flat 1-D 'dp' shards) and cannot compose with "
                "param_spec_fn tensor sharding")
        params = list(block.collect_params().values())
        self.trainable = [p for p in params if p.grad_req != "null"]
        self.aux = [p for p in params if p.grad_req == "null"]
        self.train_vals = tuple(p.data().data_jax for p in self.trainable)
        self.aux_vals = tuple(p.data().data_jax for p in self.aux)
        if optimizer is not None:
            self._opt_update = _OptimizerUpdate(
                optimizer, [v.dtype for v in self.train_vals])
            self._update = None
        else:
            self._opt_update = None
            self._update = sgd_momentum_update(lr, momentum, wd)
        self._compute_dtype = compute_dtype
        self.last_grad_norm = None
        pure_loss = _pure_loss_builder(block, loss_block, self.trainable,
                                       self.aux,
                                       aux_loss_weight=aux_loss_weight)

        repl = replicated_sharding(self.mesh)
        x_shard = (NamedSharding(self.mesh, data_spec) if data_spec is not None
                   else data_parallel_sharding(self.mesh, 1))
        if label_spec is not None:
            y_shard = NamedSharding(self.mesh, label_spec)
        elif data_spec is not None and len(data_spec):
            # labels are rank-1: shard them along the data spec's batch axis
            from jax.sharding import PartitionSpec as _P
            y_shard = NamedSharding(self.mesh, _P(data_spec[0]))
        elif data_spec is not None:
            y_shard = x_shard  # P(): replicated batch -> replicated labels
        else:
            y_shard = x_shard
        # place batch-sharded inputs via these shardings
        self.batch_sharding = x_shard
        self.label_sharding = y_shard
        self._repl = repl

        if self._zero:
            self._build_zero(pure_loss, compute_dtype, repl,
                             x_shard, y_shard)
        else:
            self._build_classic(pure_loss, compute_dtype, repl,
                                x_shard, y_shard, param_spec_fn)

    # ------------------------------------------------- replicated/dp path
    def _build_classic(self, pure_loss, cast, repl, x_shard, y_shard,
                       param_spec_fn):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        opt_update = self._opt_update
        update = self._update

        if param_spec_fn is None:
            tv_shard = aux_shard = repl
        else:
            # per-parameter shardings (tensor parallelism etc.) — the
            # optimizer state mirrors the parameter sharding
            tv_shard = tuple(
                NamedSharding(self.mesh, param_spec_fn(p.name, p.shape))
                for p in self.trainable)
            aux_shard = tuple(
                NamedSharding(self.mesh, param_spec_fn(p.name, p.shape))
                for p in self.aux)
        if opt_update is None:
            self.opt_state = sgd_momentum_init(self.train_vals)
            state_shard = tv_shard
        else:
            shapes = [v.shape for v in self.train_vals]
            self.opt_state = opt_update.init_state(
                lambda i, dt: jnp.zeros(shapes[i], dt))
            if param_spec_fn is None:
                state_shard = repl
            else:
                # one sharding per state leaf, mirroring its parameter
                state_shard = tuple(
                    tv_shard[i]
                    for i, dts in enumerate(opt_update.leaf_dtypes)
                    for _ in dts)

        def fwd_bwd(train_vals, aux_vals, x, y, key):
            def loss_of(tv):
                if cast is not None:
                    tv = tuple(v.astype(cast) if v.dtype == _np.float32
                               else v for v in tv)
                    x_ = x.astype(cast)
                else:
                    x_ = x
                return pure_loss(tv, aux_vals, x_, y, key)

            with _xray.scope(_xray.GRAD_MARKER):
                (loss, new_aux), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_vals)
            grads = tuple(g.astype(v.dtype)
                          for g, v in zip(grads, train_vals))
            return loss, grads, new_aux, _global_grad_norm(grads)

        if opt_update is None:
            def step(train_vals, opt_state, aux_vals, x, y, key):
                loss, grads, new_aux, gnorm = fwd_bwd(
                    train_vals, aux_vals, x, y, key)
                with _xray.scope(_xray.REGION_OPT):
                    new_vals, new_state = update(train_vals, grads,
                                                 opt_state)
                return loss, new_vals, new_state, new_aux, gnorm

            sig_in = (tv_shard, state_shard, aux_shard, x_shard, y_shard,
                      repl)
        else:
            def step(train_vals, opt_state, aux_vals, x, y, key, scalars):
                loss, grads, new_aux, gnorm = fwd_bwd(
                    train_vals, aux_vals, x, y, key)
                with _xray.scope(_xray.REGION_OPT):
                    new_vals, new_state = opt_update.apply(
                        train_vals, grads, opt_state, scalars)
                return loss, new_vals, new_state, new_aux, gnorm

            sig_in = (tv_shard, state_shard, aux_shard, x_shard, y_shard,
                      repl, repl)

        self.train_vals = _put(self.train_vals, tv_shard)
        self.opt_state = _put(self.opt_state, state_shard)
        self.aux_vals = _put(self.aux_vals, aux_shard)

        self._step_py = step  # un-jitted; composed by make_chained()
        self._step = jax.jit(
            step,
            in_shardings=sig_in,
            # pin outputs to the input layouts: the functional state must
            # keep its sharding across steps (otherwise the compiler may
            # re-shard e.g. a bias, and step 2's in_shardings reject it)
            out_shardings=(repl, tv_shard, state_shard, aux_shard, repl),
            donate_argnums=(0, 1, 2),
        )

    # ------------------------------------------------- ZeRO sharded path
    def _build_zero(self, pure_loss, cast, repl, x_shard, y_shard):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as _P

        opt_update = self._opt_update
        update = self._update
        mesh = self.mesh
        n = int(mesh.shape["dp"])
        flat_shard = NamedSharding(mesh, _P("dp"))
        self._flat_shard = flat_shard

        layout = []
        for p, v in zip(self.trainable, self.train_vals):
            size = int(v.size)
            layout.append({"name": p.name,
                           "shape": tuple(int(s) for s in v.shape),
                           "dtype": str(v.dtype), "size": size,
                           "padded": _padded_size(size, n)})

        def _flat_put(v, meta):
            flat = _np.zeros((meta["padded"],), _np.dtype(meta["dtype"]))
            flat[:meta["size"]] = _np.asarray(v).reshape(-1)
            return jax.device_put(flat, flat_shard)

        self.train_vals = tuple(
            _flat_put(v, m) for v, m in zip(self.train_vals, layout))
        self.aux_vals = _put(self.aux_vals, repl)

        # optimizer state is BORN on the shard layout — a jitted zeros
        # with sharded out_shardings allocates 1/n per device directly;
        # the replicated full-size state never exists at any point
        def _shard_zeros(padded, dtype):
            return jax.jit(lambda: jnp.zeros((padded,), dtype),
                           out_shardings=flat_shard)()

        if opt_update is not None:
            self.opt_state = opt_update.init_state(
                lambda i, dt: _shard_zeros(layout[i]["padded"], dt))
            leaves_per = [len(d) for d in opt_update.leaf_dtypes]
            leaf_dtypes = [[str(d) for d in dts]
                           for dts in opt_update.leaf_dtypes]
        else:
            self.opt_state = tuple(
                _shard_zeros(m["padded"], _np.dtype(m["dtype"]))
                for m in layout)
            leaves_per = [1] * len(layout)
            leaf_dtypes = [[m["dtype"]] for m in layout]

        isz = [_np.dtype(m["dtype"]).itemsize for m in layout]
        gather_bytes = sum(m["padded"] * s for m, s in zip(layout, isz))
        self.zero_layout = {
            "n": n,
            "params": layout,
            "state_leaves": leaves_per,
            "state_dtypes": leaf_dtypes,
            # logical collective payload per step: every param is
            # gathered once for the forward and its grad reduced once
            # into the shard layout
            "per_step_allgather_bytes": gather_bytes,
            "per_step_reduce_bytes": gather_bytes,
            "replicated_param_bytes": sum(
                m["size"] * s for m, s in zip(layout, isz)),
            "per_device_param_bytes": sum(
                m["padded"] // n * s for m, s in zip(layout, isz)),
            "per_device_state_bytes": sum(
                m["padded"] // n * s * l
                for m, s, l in zip(layout, isz, leaves_per)),
        }

        sizes = [m["size"] for m in layout]
        shapes = [m["shape"] for m in layout]
        wsc = jax.lax.with_sharding_constraint

        def fwd_bwd(train_flat, aux_vals, x, y, key):
            def loss_of(tf):
                # the param all-gather: constraining each flat shard to
                # replicated makes GSPMD materialize the full value on
                # every device inside this one program, overlapped with
                # forward compute
                with _xray.scope(_xray.REGION_ZERO_AG):
                    tv = tuple(
                        wsc(f, repl)[:size].reshape(shape)
                        for f, size, shape in zip(tf, sizes, shapes))
                if cast is not None:
                    tv = tuple(v.astype(cast) if v.dtype == _np.float32
                               else v for v in tv)
                    x_ = x.astype(cast)
                else:
                    x_ = x
                return pure_loss(tv, aux_vals, x_, y, key)

            with _xray.scope(_xray.GRAD_MARKER):
                (loss, new_aux), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_flat)
            # norm over the still-replicated grads: identical reduction
            # to the dp path's, so health trajectories match bit-exact
            with _xray.scope(_xray.REGION_ZERO_GNORM):
                gnorm = _global_grad_norm(grads)
            # the reduce-scatter: the backward's dp-summed grads are
            # constrained back to the 1/n flat layout — each device
            # keeps only the shard its update needs (GSPMD may lower
            # this as all-reduce + slice on backends without a fused
            # reduce-scatter; the data movement is semantically the
            # ZeRO reduce-scatter either way)
            with _xray.scope(_xray.REGION_ZERO_RS):
                grads = tuple(wsc(g.astype(f.dtype), flat_shard)
                              for g, f in zip(grads, train_flat))
            return loss, grads, new_aux, gnorm

        if opt_update is None:
            def step(train_flat, opt_flat, aux_vals, x, y, key):
                loss, grads, new_aux, gnorm = fwd_bwd(
                    train_flat, aux_vals, x, y, key)
                # elementwise update on the 1/n shards (pads carry
                # exact zeros through: zero grad -> zero update)
                with _xray.scope(_xray.REGION_OPT):
                    new_vals, new_state = update(train_flat, grads,
                                                 opt_flat)
                return loss, new_vals, new_state, new_aux, gnorm

            sig_in = (flat_shard, flat_shard, repl, x_shard, y_shard,
                      repl)
        else:
            def step(train_flat, opt_flat, aux_vals, x, y, key, scalars):
                loss, grads, new_aux, gnorm = fwd_bwd(
                    train_flat, aux_vals, x, y, key)
                with _xray.scope(_xray.REGION_OPT):
                    new_vals, new_state = opt_update.apply(
                        train_flat, grads, opt_flat, scalars)
                return loss, new_vals, new_state, new_aux, gnorm

            sig_in = (flat_shard, flat_shard, repl, x_shard, y_shard,
                      repl, repl)

        self._step_py = step
        self._step = jax.jit(
            step,
            in_shardings=sig_in,
            out_shardings=(repl, flat_shard, flat_shard, repl, repl),
            donate_argnums=(0, 1, 2),
        )

    # --------------------------------------------------------- execution
    def make_chained(self, n_steps):
        """Jit n_steps training steps as ONE device computation.

        One host dispatch covers the whole chain (lax.fori_loop carrying
        the functional state), so per-call host/relay overhead is paid
        once per n_steps instead of once per step — the device-only
        timing primitive bench.py's regression gate is built on (the
        same chaining trick as tools/bench_device_latency.py, extended
        to the full fwd+bwd+update+BN-stat step).  The per-iteration RNG
        key is fold_in(key, i), so chained(n) visits the same key
        sequence regardless of chain depth.

        The param/optimizer/aux carry is DONATED into the chain (like
        the single-step path): without donation XLA must keep the
        undonated inputs alive across the whole fori_loop, doubling
        peak param+optimizer memory.  Donation invalidates the input
        buffers, so the final carry is written back into this object's
        state — chained(n) advances training exactly like n ``__call__``
        steps (same fold_in key schedule) and repeat calls keep working.

        Works in both layouts (the ZeRO chain carries the flat shards);
        not with ``optimizer=``: its per-step scalars are refilled
        host-side each step and cannot cross a fori_loop.

        Returns fn(x, y, key) -> last_loss.
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        if self._opt_update is not None:
            raise MXNetError(
                "make_chained: per-step optimizer scalars (schedules, "
                "bias corrections) are refilled host-side each step and "
                "cannot cross a fori_loop chain; use optimizer=None "
                "(the fused sgd-momentum closure) for chained "
                "micro-benchmarks")

        step = self._step_py

        def chained(train_vals, opt_state, aux_vals, x, y, key):
            def body(i, carry):
                tv, os_, av, _ = carry
                loss, tv, os_, av, _gn = step(tv, os_, av, x, y,
                                              jax.random.fold_in(key, i))
                # fp32 carry regardless of compute dtype (bf16 steps
                # return a bf16 loss; the carry structure must be fixed)
                return (tv, os_, av, loss.astype(jnp.float32))

            init = (train_vals, opt_state, aux_vals,
                    jnp.zeros((), jnp.float32))
            tv, os_, av, loss = lax.fori_loop(0, n_steps, body, init)
            return loss, tv, os_, av

        jitted = jax.jit(chained, donate_argnums=(0, 1, 2))

        def run(x, y, key):
            loss, self.train_vals, self.opt_state, self.aux_vals = jitted(
                self.train_vals, self.opt_state, self.aux_vals, x, y, key)
            return loss

        run._jitted = jitted  # donation introspection (tests)
        return run

    def put_batch(self, x, y):
        """Place a host batch onto the mesh with the dp sharding."""
        import jax

        return (jax.device_put(_np.asarray(x), self.batch_sharding),
                jax.device_put(_np.asarray(y), self.label_sharding))

    def __call__(self, x, y):
        """One training step on device arrays/numpy; returns loss (async)."""
        import jax

        if not isinstance(x, jax.Array):
            x, y = self.put_batch(x, y)
        key = _random.next_key()
        args = [self.train_vals, self.opt_state, self.aux_vals, x, y, key]
        if self._opt_update is not None:
            args.append(self._opt_update.host_scalars())
        (loss, self.train_vals, self.opt_state, self.aux_vals,
         gnorm) = self._step(*args)
        self.last_grad_norm = gnorm
        if self._zero:
            zl = self.zero_layout
            _rts.inc("zero_steps")
            _rts.inc("zero_allgather_bytes",
                     zl["per_step_allgather_bytes"])
            _rts.inc("zero_reduce_bytes", zl["per_step_reduce_bytes"])
        if _health._state["on"]:
            hm = _health.monitor()
            if hm is not None:
                hm.observe_scalar("grad_norm", gnorm)
        return loss

    def sync_to_params(self):
        """Write functional values back into the Gluon Parameters.

        Values are gathered off the mesh first: the Parameters feed the
        normal eager API afterwards, and a mesh-committed array mixed
        with default-device eager operands is a placement error on
        multi-device hosts.  In the ZeRO layout each flat value is
        unpadded and reshaped back to the parameter's shape."""
        import jax.numpy as jnp

        if self._zero:
            for p, v, m in zip(self.trainable, self.train_vals,
                               self.zero_layout["params"]):
                host = jnp.asarray(
                    _np.asarray(v)[:m["size"]].reshape(m["shape"]))
                for d in p._data:
                    d._assign(host)
        else:
            for p, v in zip(self.trainable, self.train_vals):
                host = jnp.asarray(_np.asarray(v))
                for d in p._data:
                    d._assign(host)
        for p, v in zip(self.aux, self.aux_vals):
            host = jnp.asarray(_np.asarray(v))
            for d in p._data:
                d._assign(host)

    # ------------------------------------------------ sharded checkpoint
    def zero_shard_payloads(self):
        """``{rank: payload}`` for every locally-addressable 'dp'
        position — the per-rank shard files of a sharded checkpoint.
        Each payload carries exactly the 1/n slice that rank owns
        (params + optimizer-state leaves), so a rank never persists
        another rank's bytes; in a multi-host run each process sees
        only its own ranks here."""
        if not self._zero:
            raise MXNetError(
                "zero_shard_payloads: this step was not built with "
                "zero=True")
        n = self.zero_layout["n"]
        out = {}

        def collect(vals, kind):
            for j, v in enumerate(vals):
                shard_len = int(v.shape[0]) // n
                for s in v.addressable_shards:
                    rank = int(s.index[0].start or 0) // shard_len
                    slot = out.setdefault(
                        rank, {"params": {}, "state": {}})
                    slot[kind][j] = _np.asarray(s.data)

        collect(self.train_vals, "params")
        collect(self.opt_state, "state")
        return out

    def save_zero(self, step, mgr=None):
        """Commit a sharded checkpoint: one global manifest over
        per-rank shard files (``CheckpointManager.save_sharded`` — the
        rank-0 commit barrier lives there), layout metadata in the
        ``aux`` sideband so resume can re-shard."""
        from .. import checkpoint as _ckpt

        mgr = mgr if mgr is not None else _ckpt.manager()
        if mgr is None:
            raise MXNetError(
                "save_zero: no checkpoint manager — call "
                "checkpoint.enable(directory) first or pass mgr=")
        n = self.zero_layout["n"]
        files = {"zero-shard-%05d-of-%05d" % (r, n): payload
                 for r, payload in self.zero_shard_payloads().items()}
        aux = {"zero_layout": self.zero_layout}
        if self._opt_update is not None:
            # host-side optimizer hyper-state (update counts drive
            # Adam-family bias correction; schedulers drive lr) — the
            # device shards alone do not make the step resumable
            aux["optimizer"] = _ckpt._strip_optimizer(
                self._opt_update.opt)
        return mgr.save_sharded(step, files, aux=aux)

    def restore_zero(self, manifest, mgr=None):
        """Load a sharded checkpoint back into this step's flat shards,
        RE-SHARDING when the checkpoint's dp width differs from the
        current mesh (the layout-change resume path): each full flat
        vector is rebuilt from the old ranks' slices, stripped of the
        old padding, re-padded to the current multiple and placed onto
        the current 'dp' layout.  Restores the RNG stream too; returns
        the checkpoint step."""
        import jax

        from .. import checkpoint as _ckpt

        if not self._zero:
            raise MXNetError(
                "restore_zero: this step was not built with zero=True")
        mgr = mgr if mgr is not None else _ckpt.manager()
        if mgr is None:
            raise MXNetError("restore_zero: no checkpoint manager")
        aux = mgr.load_aux(manifest)
        if not aux or "zero_layout" not in aux:
            raise MXNetError(
                "restore_zero: checkpoint %s carries no zero_layout "
                "sideband — not a sharded checkpoint"
                % manifest.get("path"))
        old = aux["zero_layout"]
        ranks = mgr.load_shard_files(manifest)
        if len(ranks) != old["n"]:
            raise MXNetError(
                "restore_zero: checkpoint %s has %d of %d rank shard "
                "files" % (manifest.get("path"), len(ranks), old["n"]))
        if old["state_leaves"] != self.zero_layout["state_leaves"]:
            raise MXNetError(
                "restore_zero: optimizer state structure changed "
                "(%r leaves saved vs %r now) — restore with the same "
                "optimizer family"
                % (old["state_leaves"], self.zero_layout["state_leaves"]))

        def rebuild(kind, j, meta_old, meta_new, dtype):
            full = _np.concatenate(
                [ranks[r][kind][j] for r in range(old["n"])])
            flat = _np.zeros((meta_new["padded"],), dtype)
            flat[:meta_new["size"]] = full[:meta_old["size"]]
            return jax.device_put(flat, self._flat_shard)

        new_params = []
        for j, (mo, mn) in enumerate(zip(old["params"],
                                         self.zero_layout["params"])):
            if (mo["name"], mo["size"]) != (mn["name"], mn["size"]):
                raise MXNetError(
                    "restore_zero: parameter %d mismatch (%s/%d saved "
                    "vs %s/%d now) — the model changed"
                    % (j, mo["name"], mo["size"], mn["name"], mn["size"]))
            new_params.append(
                rebuild("params", j, mo, mn, _np.dtype(mn["dtype"])))
        self.train_vals = tuple(new_params)

        new_state = []
        leaf = 0
        for i, count in enumerate(self.zero_layout["state_leaves"]):
            mo, mn = old["params"][i], self.zero_layout["params"][i]
            for c in range(count):
                dt = _np.dtype(self.zero_layout["state_dtypes"][i][c])
                new_state.append(rebuild("state", leaf, mo, mn, dt))
                leaf += 1
        self.opt_state = tuple(new_state)
        blob = aux.get("optimizer")
        if blob is not None and self._opt_update is not None:
            import pickle

            src = pickle.loads(blob)
            hyper = dict(src.__dict__)
            hyper.pop("param_dict", None)
            self._opt_update.opt.__dict__.update(hyper)
        rng = manifest.get("rng")
        if rng:
            _random.set_state(rng)
        return int(manifest.get("step", 0))


#: ISSUE-14 spelling: ``GluonStep(..., zero=True)``
GluonStep = GluonTrainStep
