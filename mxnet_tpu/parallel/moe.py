"""Mixture-of-experts with expert parallelism over the 'ep' mesh axis.

Absent in the reference (SURVEY.md §2.3: EP/MoE — NO); provided here
because expert parallelism shapes the core design of a TPU framework.

TPU-native formulation (Mesh-TensorFlow / GShard style): routing is
expressed as dense einsums against a one-hot dispatch tensor with a
fixed per-expert capacity — static shapes, MXU-friendly, no
data-dependent gather.  Sharding the expert axis of the weights and the
dispatched activations over 'ep' makes GSPMD insert the all-to-alls;
there is no hand-written communication here at all, which is exactly
how EP should look under XLA.

    moe = MoEFFN(d_model=512, d_hidden=2048, n_experts=8)
    params = moe.init(rng)
    y, aux_loss = moe.apply(params, x)          # x: (batch, seq, d)

Shard with `moe.param_specs()` / data over 'dp' under jit; works
unsharded on one device too.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["MoEFFN"]


class MoEFFN:
    """Top-2 gated expert feed-forward block (GShard routing rules).

    capacity_factor bounds tokens per expert: C = ceil(cf * T * 2 / E)
    per batch row; overflow tokens drop to the residual path (their
    combine weight is 0) — the standard fixed-capacity formulation.
    """

    def __init__(self, d_model, d_hidden, n_experts, capacity_factor=1.25,
                 axis="ep"):
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.axis = axis

    def init(self, rng, dtype=jnp.float32):
        import numpy as np

        rs = np.random.RandomState(
            int(jax.random.randint(rng, (), 0, 2**31 - 1)))
        d, h, e = self.d_model, self.d_hidden, self.n_experts
        s1 = (2.0 / (d + h)) ** 0.5
        return {
            "gate": jnp.asarray(rs.randn(d, e) * (1.0 / d) ** 0.5,
                                dtype=dtype),
            "wi": jnp.asarray(rs.randn(e, d, h) * s1, dtype=dtype),
            "wo": jnp.asarray(rs.randn(e, h, d) * s1, dtype=dtype),
        }

    def param_specs(self):
        """PartitionSpecs sharding the expert axis over 'ep'."""
        from jax.sharding import PartitionSpec as P

        return {"gate": P(), "wi": P(self.axis, None, None),
                "wo": P(self.axis, None, None)}

    def capacity(self, tokens_per_row):
        import math

        return max(1, math.ceil(self.capacity_factor * tokens_per_row * 2
                                / self.n_experts))

    def apply(self, params, x):
        """x: (B, S, d) → (y, aux_loss).

        aux_loss is the GShard load-balancing loss (mean over experts of
        fraction_routed * mean_gate_prob * E); add it to the task loss.
        """
        B, S, d = x.shape
        E = self.n_experts
        C = self.capacity(S)

        logits = jnp.einsum("bsd,de->bse", x, params["gate"])
        probs = jax.nn.softmax(logits, axis=-1)

        # top-2 expert choice per token
        g1 = jnp.argmax(probs, axis=-1)                      # (B, S)
        p1 = jnp.take_along_axis(probs, g1[..., None], -1)[..., 0]
        masked = probs - jax.nn.one_hot(g1, E) * probs
        g2 = jnp.argmax(masked, axis=-1)
        p2 = jnp.take_along_axis(masked, g2[..., None], -1)[..., 0]

        # position of each token in its expert's buffer (capacity C);
        # tokens past C overflow (mask -> 0)
        def positions(g):
            onehot = jax.nn.one_hot(g, E)                    # (B, S, E)
            pos = jnp.cumsum(onehot, axis=1) * onehot        # 1-based
            return onehot, pos
        oh1, pos1 = positions(g1)
        # expert-1 claims count against expert-2's buffer too.  A
        # zero-probability runner-up (top-1 prob ~1.0 leaves `masked`
        # all-zero, argmax falls back to expert 0) is masked out here so
        # it neither dispatches nor consumes a capacity slot
        oh2_raw = jax.nn.one_hot(g2, E) * (p2 > 0)[..., None]
        used = jnp.sum(oh1, axis=1, keepdims=True)           # (B, 1, E)
        pos2 = (jnp.cumsum(oh2_raw, axis=1) + used) * oh2_raw
        oh2 = oh2_raw

        keep1 = (pos1 > 0) & (pos1 <= C)
        keep2 = (pos2 > 0) & (pos2 <= C)

        # normalized combine weights; dropped tokens keep weight 0
        denom = p1 + p2 + 1e-9
        w1 = jnp.where(jnp.any(keep1, -1), p1 / denom, 0.0)
        w2 = jnp.where(jnp.any(keep2, -1), p2 / denom, 0.0)

        slot1 = jax.nn.one_hot(
            (jnp.sum(pos1, -1) - 1).astype(jnp.int32), C)   # (B, S, C)
        slot2 = jax.nn.one_hot(
            (jnp.sum(pos2, -1) - 1).astype(jnp.int32), C)
        # dispatch tensor (B, S, E, C): token s -> (expert, slot)
        disp = (keep1[..., None] * oh1[..., None] * slot1[:, :, None, :] +
                keep2[..., None] * oh2[..., None] * slot2[:, :, None, :])
        comb = (w1[..., None, None] * keep1[..., None] * oh1[..., None] *
                slot1[:, :, None, :] +
                w2[..., None, None] * keep2[..., None] * oh2[..., None] *
                slot2[:, :, None, :])

        # all-to-all happens HERE under GSPMD: expert axis of `buf`
        # is sharded over 'ep' while s is dp/sp-sharded
        buf = jnp.einsum("bsec,bsd->becd", disp, x)          # (B, E, C, d)
        hid = jax.nn.relu(jnp.einsum("becd,edh->bech", buf, params["wi"]))
        out = jnp.einsum("bech,ehd->becd", hid, params["wo"])
        y = jnp.einsum("bsec,becd->bsd", comb, out)

        # load-balancing auxiliary loss (GShard eq. 4): encourages the
        # top-1 routing fraction to match the mean gate probability
        frac = jnp.mean(oh1, axis=(0, 1))                    # (E,)
        mean_prob = jnp.mean(probs, axis=(0, 1))
        aux = jnp.sum(frac * mean_prob) * E
        return y, aux
