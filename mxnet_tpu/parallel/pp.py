"""Pipeline parallelism (GPipe) over the 'pp' mesh axis.

The reference's closest capability is manual model parallelism via
`group2ctx` ctx-groups (src/executor/graph_executor.cc:1628) and
step-wise `PartialForward` (graph_executor.cc:68); it has no pipeline
schedule.  This module goes beyond parity with a TPU-native GPipe:

- each 'pp' rank holds ONE stage's parameters (stacked pytree sharded on
  the leading axis);
- microbatches stream through the ring: every tick each rank applies its
  stage, then `lax.ppermute` passes activations to the next rank over
  ICI — the classic fill/steady/drain schedule, M + P - 1 ticks for M
  microbatches on P stages;
- the whole schedule is a `lax.scan` inside `shard_map`, so XLA overlaps
  the neighbour transfer with the next tick's compute, and `jax.grad`
  differentiates straight through it (ppermute's transpose is the
  reverse-direction ppermute) — backward runs the reverse pipeline
  automatically, no hand-written 1F1B machinery.

Stages must be shape-homogeneous (activation in == activation out),
the standard case for stacked transformer blocks; the embed/head live
outside the pipelined middle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map as _shard_map_raw
    _REP_KWARG = "check_vma"
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_raw
    _REP_KWARG = "check_rep"


def _shard_map(fn, **kw):
    """Version shim: the replication-check kwarg was renamed check_rep →
    check_vma when shard_map moved out of jax.experimental."""
    kw[_REP_KWARG] = False
    return _shard_map_raw(fn, **kw)


__all__ = ["GPipe", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with leading stage axis
    (shard it over 'pp')."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


class GPipe:
    """Compile `stage_fn` into a pipelined forward over mesh axis 'pp'.

    Parameters
    ----------
    stage_fn : (stage_params, x) -> y with y.shape == x.shape; with
        ``has_aux`` the signature is (stage_params, x, aux) ->
        (y, new_aux) where aux is this stage's mutable state (BatchNorm
        running stats), threaded through the schedule per rank
    mesh : jax Mesh with a 'pp' axis covering all its devices' stages
    n_microbatches : how many microbatches the global batch splits into
        (≥ n_stages keeps the bubble fraction at (P-1)/(M+P-1))
    axis : mesh axis name
    has_aux : stages carry aux state.  Aux updates chain across the
        stage's microbatches (EMA applied once per VALID tick — fill
        and drain ticks, where a rank chews zero-padding, leave the aux
        untouched), so the semantics match training with
        microbatch-sized batches — the standard GPipe BatchNorm
        contract.

    Call with (stacked_params, x) — or (stacked_params, x, stacked_aux)
    with ``has_aux`` — where stacked trees have a leading stage axis and
    x is the GLOBAL batch (dim 0 divisible by n_microbatches); returns
    the transformed global batch (plus the updated stacked aux).
    """

    def __init__(self, stage_fn, mesh, n_microbatches=None, axis="pp",
                 has_aux=False, batch_spec=None, param_specs=None):
        self.mesh = mesh
        self.axis = axis
        self.n_stages = mesh.shape[axis]
        self.n_micro = n_microbatches or self.n_stages
        self.has_aux = has_aux
        # ONE schedule implementation: aux-free stage fns are adapted to
        # the (params, x, aux) -> (y, aux) signature with an empty aux
        # tree, so the subtle fill/steady/drain logic exists once
        if has_aux:
            self.stage_fn = stage_fn
        else:
            self.stage_fn = lambda p, x, aux: (stage_fn(p, x), aux)

        from jax.sharding import PartitionSpec as P

        # batch_spec: how x (and the output) is laid over the OTHER
        # mesh axes — e.g. P('dp', None) composes the pipeline with
        # data parallelism (each dp slice streams its own microbatches).
        # param_specs: a pytree(-prefix) of specs for the stacked stage
        # params when stage weights also shard over other axes (e.g.
        # P('pp', None, 'tp') for Megatron column-parallel stages); the
        # default P(axis) shards the stage dim only.
        self._fn = _shard_map(
            self._device_program, mesh=mesh,
            in_specs=(P(axis) if param_specs is None else param_specs,
                      P() if batch_spec is None else batch_spec,
                      P(axis)),
            out_specs=(P() if batch_spec is None else batch_spec,
                       P(axis)))

    def _device_program(self, params, x, aux):
        """Runs per-device: params/aux carry a leading stage axis of
        size 1 (this rank's stage); x is the full global batch.  Aux
        rides the scan carry; a tick's update is kept only when the
        tick processed one of this rank's M real microbatches (rank i
        is valid for i <= t <= i + M - 1) — fill/drain ticks chew
        zero-padding and must not touch stage state."""
        axis, M = self.axis, self.n_micro
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        aux0 = jax.tree_util.tree_map(lambda a: a[0], aux)
        i = lax.axis_index(axis)
        P = self.n_stages

        gb = x.shape[0]
        assert gb % M == 0, "global batch %d %% %d microbatches" % (gb, M)
        micro = x.reshape((M, gb // M) + x.shape[1:])

        perm = [(j, (j + 1) % P) for j in range(P)]
        state = jnp.zeros_like(micro[0])
        outs = jnp.zeros_like(micro)

        def tick(carry, t):
            state, outs, aux = carry
            # stage 0 ingests microbatch t during the fill phase
            inp = micro[jnp.clip(t, 0, M - 1)]
            cur = jnp.where(i == 0, jnp.where(t < M, inp, state), state)
            y, new_aux = self.stage_fn(params, cur, aux)
            valid = (t >= i) & (t <= i + M - 1)
            aux = jax.tree_util.tree_map(
                lambda n, o: jnp.where(valid, n, o), new_aux, aux)
            # the last stage emits microbatch m = t - (P - 1)
            m = t - (P - 1)
            written = lax.dynamic_update_index_in_dim(
                outs, y, jnp.clip(m, 0, M - 1), 0)
            outs = jnp.where((i == P - 1) & (m >= 0), written, outs)
            state = lax.ppermute(y, axis, perm)
            return (state, outs, aux), None

        (_, outs, aux_f), _ = lax.scan(tick, (state, outs, aux0),
                                       jnp.arange(M + P - 1))
        # result lives on the last rank; make it mesh-invariant
        outs = lax.psum(jnp.where(i == P - 1, outs, jnp.zeros_like(outs)),
                        axis)
        return (outs.reshape((gb,) + x.shape[1:]),
                jax.tree_util.tree_map(lambda a: a[None], aux_f))

    def __call__(self, stacked_params, x, stacked_aux=None):
        out, aux = self._fn(stacked_params, x,
                            {} if stacked_aux is None else stacked_aux)
        if self.has_aux:
            return out, aux
        return out
