"""Device meshes and collectives.

Reference analog: the device topology planning in src/kvstore/
gpu_topology.h (tree-reduce link-penalty search) and comm.h device
communication.  On TPU none of that is needed: the mesh axes map onto
the physical torus by XLA, and collectives ride ICI.  ``create_mesh``
is the single entry point: axes ('dp','tp','pp','sp','ep') with sizes
chosen by the caller (1 collapses the axis).
"""

from __future__ import annotations

import numpy as _np

_DEFAULT_MESH = None

AXIS_ORDER = ("pp", "dp", "sp", "ep", "tp")  # tp innermost → fastest ICI links


def create_mesh(axis_sizes=None, devices=None):
    """Create a ``jax.sharding.Mesh``.

    axis_sizes: dict like {'dp': 4, 'tp': 2}; remaining devices must be
    covered (product == ndev).  Default: all devices on 'dp'.
    """
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = {"dp": n}
    sizes = [int(axis_sizes.get(a, 1)) for a in AXIS_ORDER]
    prod = int(_np.prod(sizes))
    if prod != n:
        raise ValueError("mesh axes %r product %d != %d devices"
                         % (axis_sizes, prod, n))
    arr = _np.asarray(devices).reshape(sizes)
    return Mesh(arr, AXIS_ORDER)


def set_default_mesh(mesh):
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def get_default_mesh():
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = create_mesh()
    return _DEFAULT_MESH


def data_parallel_sharding(mesh, ndim):
    """NamedSharding: dim0 over 'dp', rest replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P("dp", *([None] * (ndim - 1))) if ndim > 0 else P()
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def host_allreduce(value):
    """Sum a host-side array across all devices/processes.

    Used by the dist kvstore barrier/reduction path (the DCN analog of
    ps-lite push aggregation, kvstore_dist_server.h:346).  Single-process
    fallback: identity.
    """
    import jax

    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(_np.asarray(value))
    return gathered.sum(axis=0)
