"""``mxnet_tpu.parallel`` — meshes, sharded training steps, collectives.

This is the TPU-native replacement for the reference's distributed stack
(SURVEY.md §2.3): instead of NCCL reduce (kvstore_nccl.h), P2P/tree
reduce (comm.h, comm_tree.h, gpu_topology.h) and the ps-lite parameter
server (kvstore_dist*.h), everything is a ``jax.sharding.Mesh`` +
sharding annotations; XLA inserts psum/all-gather/reduce-scatter over
ICI (in-slice) and DCN (cross-slice).
"""

from .mesh import (create_mesh, data_parallel_sharding, get_default_mesh,  # noqa: F401
                   host_allreduce, set_default_mesh)
from .data_parallel import DataParallelStep, make_train_step  # noqa: F401
from .gluon_step import GluonTrainStep  # noqa: F401
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
