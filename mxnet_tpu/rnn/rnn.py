"""RNN checkpoint helpers (reference: python/mxnet/rnn/rnn.py).

Checkpoints are stored UNPACKED (per-gate names) so they interchange
between fused and unfused cells and remain inspectable; loading packs
them back into whatever layout the given cells consume.
"""

from __future__ import annotations

import warnings

from ..model import load_checkpoint, save_checkpoint
from .rnn_cell import BaseRNNCell

__all__ = ["rnn_unroll", "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def _as_cell_list(cells):
    cells = [cells] if isinstance(cells, BaseRNNCell) else list(cells)
    return cells


def rnn_unroll(cell, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC"):
    """Deprecated alias kept for reference parity; call cell.unroll."""
    warnings.warn("rnn_unroll is deprecated; call cell.unroll directly")
    return cell.unroll(length=length, inputs=inputs,
                       begin_state=begin_state, layout=layout)


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Save symbol+params with every cell's weights unpacked
    (reference: rnn.py save_rnn_checkpoint)."""
    for cell in _as_cell_list(cells):
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load a checkpoint saved by :func:`save_rnn_checkpoint`, re-packing
    weights for the given cells (reference: rnn.py load_rnn_checkpoint)."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    for cell in _as_cell_list(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback variant of :func:`save_rnn_checkpoint`
    (reference: rnn.py do_rnn_checkpoint)."""
    period = max(1, int(period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
