"""Symbolic RNN cells (reference: python/mxnet/rnn/rnn_cell.py:1-1437).

API-parity reimplementation in this repo's idiom.  The cells build
Symbol graphs step-by-step (python loop over time → one staged XLA
module at bind), while :class:`FusedRNNCell` emits the monolithic
``RNN`` op, which lowers to a ``lax.scan`` per layer/direction with the
input projection hoisted into one MXU matmul (ops/rnn.py — the
TPU-native counterpart of the reference's cuDNN path,
src/operator/cudnn_rnn-inl.h).

Parameter-name contract (checkpoints must round-trip with the
reference): packed names are ``{prefix}i2h_weight`` / ``i2h_bias`` /
``h2h_weight`` / ``h2h_bias``; per-gate unpacked names insert the gate
suffix (``{prefix}i2h{gate}_weight`` with gates ``_i,_f,_c,_o`` for
lstm, ``_r,_z,_o`` for gru).  The fused cell's single vector is
``{prefix}parameters`` in the gates-major cuDNN layout of ops/rnn.py.

One conscious divergence: the reference writes unknown batch as 0 in
``begin_state`` shapes and resolves it at bind; XLA needs concrete
shapes, so default begin states are zeros with batch dim **1** and
every consumer broadcasts (B,H)⊕(1,H).  Feeding real states of shape
(B,H) works unchanged.
"""

from __future__ import annotations

from .. import symbol
from ..base import MXNetError

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "BaseConvRNNCell", "ConvRNNCell",
           "ConvLSTMCell", "ConvGRUCell"]

# gate suffix tables, fused-op (cuDNN) order; ops/rnn.py slices in this
# order, and the unfused cells compute in this order, so one table
# serves both
_GATES = {
    "rnn_relu": ("",),
    "rnn_tanh": ("",),
    "lstm": ("_i", "_f", "_c", "_o"),
    "gru": ("_r", "_z", "_o"),
}


class RNNParams:
    """Shared container of symbolic variables, keyed by prefixed name
    (reference: rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        full = self._prefix + name
        try:
            return self._params[full]
        except KeyError:
            v = symbol.Variable(full, **kwargs)
            self._params[full] = v
            return v


def _sum_states(cells, member, *args, **kwargs):
    """Concatenate a per-cell list-valued member across cells."""
    out = []
    for c in cells:
        v = getattr(c, member)
        out.extend(v(*args, **kwargs) if callable(v) else v)
    return out


def _chain_dicts(cells, member, args):
    for c in cells:
        args = getattr(c, member)(args)
    return args


def _as_steps(inputs, length, layout):
    """Inputs → list of per-step (B, ...) symbols + the time axis."""
    t_axis = layout.find("T")
    if isinstance(inputs, symbol.Symbol):
        if len(inputs.list_outputs()) != 1:
            raise MXNetError("unroll: grouped symbols are ambiguous; pass "
                             "a list of per-step symbols instead")
        steps = list(symbol.SliceChannel(inputs, axis=t_axis,
                                         num_outputs=length,
                                         squeeze_axis=1))
        return steps, t_axis
    if length is not None and len(inputs) != length:
        raise MXNetError("unroll: got %d inputs for length=%d"
                         % (len(inputs), length))
    return list(inputs), t_axis


def _as_merged(outputs, t_axis):
    """Per-step symbols → one (.., T, ..) symbol stacked on t_axis."""
    expanded = [symbol.expand_dims(o, axis=t_axis) for o in outputs]
    return symbol.Concat(*expanded, dim=t_axis)


def _shape_outputs(outputs, length, layout, merge):
    """Apply the merge_outputs contract to a list or merged symbol."""
    t_axis = layout.find("T")
    is_merged = isinstance(outputs, symbol.Symbol)
    if merge is None:
        return outputs
    if merge and not is_merged:
        return _as_merged(outputs, t_axis)
    if not merge and is_merged:
        return list(symbol.SliceChannel(outputs, axis=t_axis,
                                        num_outputs=length, squeeze_axis=1))
    return outputs


class BaseRNNCell:
    """Abstract symbolic cell: step with ``__call__``, iterate with
    ``unroll`` (reference: rnn_cell.py BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        self._own_params = params is None
        self._params = RNNParams(prefix) if params is None else params
        self._prefix = prefix
        self._modified = False
        self.reset()

    # -- bookkeeping -------------------------------------------------------
    def reset(self):
        """Forget step/state counters so the cell can build a new graph."""
        self._counter = -1
        self._init_counter = -1
        for c in getattr(self, "_cells", ()):
            c.reset()

    @property
    def params(self):
        self._own_params = False
        return self._params

    # -- state contract ----------------------------------------------------
    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, **kwargs):
        if self._modified:
            raise MXNetError(
                "cell was wrapped by a modifier (Zoneout/Residual/...); "
                "request begin_state from the modifier instead")
        func = func or symbol.zeros
        states = []
        for info in self.state_info:
            self._init_counter += 1
            kw = dict(kwargs)
            if info is not None:
                kw.update(info)
            # Variables keep the reference's deferred-0 batch dim — the
            # partial-shape unification pass resolves it at bind time
            # (r4); concrete creators (zeros/...) need real dims, so a
            # batch-1 stand-in remains there (broadcasting restores the
            # true batch on first use)
            if "shape" in kw and func is not symbol.Variable:
                kw["shape"] = tuple(1 if d == 0 else d for d in kw["shape"])
            kw.pop("__layout__", None)
            states.append(func(
                name="%sbegin_state_%d" % (self._prefix, self._init_counter),
                **kw))
        return states

    # -- weight layout -----------------------------------------------------
    def unpack_weights(self, args):
        """Split packed gate matrices into per-gate entries
        (reference semantics: BaseRNNCell.unpack_weights)."""
        gates = self._gate_names
        if not gates:
            return dict(args)
        out = dict(args)
        h = self._num_hidden
        for part in ("i2h", "h2h"):
            w = out.pop("%s%s_weight" % (self._prefix, part))
            b = out.pop("%s%s_bias" % (self._prefix, part))
            for j, g in enumerate(gates):
                out["%s%s%s_weight" % (self._prefix, part, g)] = \
                    w[j * h:(j + 1) * h].copy()
                out["%s%s%s_bias" % (self._prefix, part, g)] = \
                    b[j * h:(j + 1) * h].copy()
        return out

    def pack_weights(self, args):
        """Inverse of :meth:`unpack_weights`."""
        gates = self._gate_names
        if not gates:
            return dict(args)
        from .. import ndarray as nd

        out = dict(args)
        for part in ("i2h", "h2h"):
            ws, bs = [], []
            for g in gates:
                ws.append(out.pop("%s%s%s_weight" % (self._prefix, part, g)))
                bs.append(out.pop("%s%s%s_bias" % (self._prefix, part, g)))
            out["%s%s_weight" % (self._prefix, part)] = nd.concatenate(ws)
            out["%s%s_bias" % (self._prefix, part)] = nd.concatenate(bs)
        return out

    # -- stepping ----------------------------------------------------------
    def __call__(self, inputs, states):
        """One step: (B, in), [states] → output (B, H), [new states]."""
        raise NotImplementedError

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Python-loop unroll; the whole DAG stages into one XLA module
        at bind, so there is no per-step dispatch at runtime."""
        self.reset()
        steps, t_axis = _as_steps(inputs, length, layout)
        states = begin_state if begin_state is not None else \
            self.begin_state()
        outputs = []
        for x in steps:
            out, states = self(x, states)
            outputs.append(out)
        if merge_outputs:
            return _as_merged(outputs, t_axis), states
        return outputs, states

    def _activate(self, x, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(x, act_type=activation, **kwargs)
        return activation(x, **kwargs)

    def _step_name(self):
        self._counter += 1
        return "%st%d_" % (self._prefix, self._counter)


class _SingleGateSetCell(BaseRNNCell):
    """Shared plumbing for cells with one fused i2h/h2h matmul pair."""

    def __init__(self, num_hidden, prefix, params, i2h_bias_init=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        p = self.params
        self._w = {"i2h_weight": p.get("i2h_weight"),
                   "h2h_weight": p.get("h2h_weight"),
                   "h2h_bias": p.get("h2h_bias"),
                   "i2h_bias": p.get("i2h_bias", init=i2h_bias_init)
                   if i2h_bias_init is not None else p.get("i2h_bias")}

    def _projections(self, inputs, h_prev, step_name):
        n = self._num_hidden * len(self._gate_names)
        i2h = symbol.FullyConnected(
            data=inputs, weight=self._w["i2h_weight"],
            bias=self._w["i2h_bias"], num_hidden=n,
            name="%si2h" % step_name)
        h2h = symbol.FullyConnected(
            data=h_prev, weight=self._w["h2h_weight"],
            bias=self._w["h2h_bias"], num_hidden=n,
            name="%sh2h" % step_name)
        return i2h, h2h


class RNNCell(_SingleGateSetCell):
    """Elman cell: h' = act(W_x x + W_h h + b)
    (reference: rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(num_hidden, prefix, params)
        self._activation = activation

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        name = self._step_name()
        i2h, h2h = self._projections(inputs, states[0], name)
        out = self._activate(i2h + h2h, self._activation,
                             name="%sout" % name)
        return out, [out]


class LSTMCell(_SingleGateSetCell):
    """LSTM cell, gates (i, f, c, o), forget bias folded into i2h_bias
    init (reference: rnn_cell.py LSTMCell, Jozefowicz et al. 2015)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        from ..initializer import LSTMBias

        super().__init__(num_hidden, prefix, params,
                         i2h_bias_init=LSTMBias(forget_bias=forget_bias))

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        name = self._step_name()
        i2h, h2h = self._projections(inputs, states[0], name)
        g_i, g_f, g_c, g_o = symbol.SliceChannel(
            i2h + h2h, num_outputs=4, name="%sslice" % name)
        i = symbol.Activation(g_i, act_type="sigmoid", name="%si" % name)
        f = symbol.Activation(g_f, act_type="sigmoid", name="%sf" % name)
        c_tilde = symbol.Activation(g_c, act_type="tanh", name="%sc" % name)
        o = symbol.Activation(g_o, act_type="sigmoid", name="%so" % name)
        next_c = symbol.elemwise_add(f * states[1], i * c_tilde,
                                     name="%sstate" % name)
        next_h = symbol.elemwise_mul(
            o, symbol.Activation(next_c, act_type="tanh"),
            name="%sout" % name)
        return next_h, [next_h, next_c]


class GRUCell(_SingleGateSetCell):
    """GRU cell in the cuDNN formulation (reset gate applied to the h2h
    projection; reference: rnn_cell.py GRUCell)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(num_hidden, prefix, params)

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        name = self._step_name()
        h_prev = states[0]
        i2h, h2h = self._projections(inputs, h_prev, name)
        xr, xz, xn = symbol.SliceChannel(i2h, num_outputs=3,
                                         name="%s_i2h_slice" % name)
        hr, hz, hn = symbol.SliceChannel(h2h, num_outputs=3,
                                         name="%s_h2h_slice" % name)
        r = symbol.Activation(xr + hr, act_type="sigmoid",
                              name="%s_r_act" % name)
        z = symbol.Activation(xz + hz, act_type="sigmoid",
                              name="%s_z_act" % name)
        cand = symbol.Activation(xn + r * hn, act_type="tanh",
                                 name="%s_h_act" % name)
        next_h = symbol.elemwise_add((1.0 - z) * cand, z * h_prev,
                                     name="%sout" % name)
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-stack cell over the monolithic ``RNN`` op
    (reference: rnn_cell.py FusedRNNCell; TPU impl ops/rnn.py).

    The single packed parameter vector uses the gates-major cuDNN
    layout; :meth:`unpack_weights` yields the same per-layer,
    per-direction names the reference produces, so fused↔unfused
    checkpoints interchange."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        from ..initializer import FusedRNN

        prefix = "%s_" % mode if prefix is None else prefix
        super().__init__(prefix=prefix, params=params)
        if mode not in _GATES:
            raise MXNetError("unknown RNN mode %r" % (mode,))
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = ("l", "r") if bidirectional else ("l",)
        self._parameter = self.params.get(
            "parameters", init=FusedRNN(None, num_hidden, num_layers, mode,
                                        bidirectional, forget_bias))

    @property
    def state_info(self):
        depth = len(self._directions) * self._num_layers
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (depth, 0, self._num_hidden),
                 "__layout__": "LNC"} for _ in range(n)]

    @property
    def _gate_names(self):
        return _GATES[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    # -- packed-vector layout (must mirror ops/rnn.py _unpack) -------------
    def _walk_slices(self, num_input):
        """Yield (unpacked_name, offset, shape) triples over the packed
        vector in the exact order ops/rnn.py consumes it: all weights
        (layer → direction → i2h gates → h2h gates), then all biases."""
        h = self._num_hidden
        b = len(self._directions)
        pos = 0

        def cell_pieces(stem, kind, in_dim):
            nonlocal pos
            shape = (h, in_dim) if kind.endswith("weight") else (h,)
            n = h * in_dim if kind.endswith("weight") else h
            for g in self._gate_names:
                start = pos
                pos += n
                yield "%s%s%s_%s" % (stem, kind[:3], g,
                                     kind[4:]), start, shape

        for layer in range(self._num_layers):
            in_dim = num_input if layer == 0 else h * b
            for d in self._directions:
                stem = "%s%s%d_" % (self._prefix, d, layer)
                yield from cell_pieces(stem, "i2h_weight", in_dim)
                yield from cell_pieces(stem, "h2h_weight", h)
        for layer in range(self._num_layers):
            for d in self._directions:
                stem = "%s%s%d_" % (self._prefix, d, layer)
                yield from cell_pieces(stem, "i2h_bias", 1)
                yield from cell_pieces(stem, "h2h_bias", 1)

    def _infer_num_input(self, total):
        h, b, m = self._num_hidden, len(self._directions), self._num_gates
        return total // (b * h * m) - (self._num_layers - 1) * (h + b * h + 2) \
            - h - 2

    def unpack_weights(self, args):
        out = dict(args)
        vec = out.pop(self._parameter.name)
        ni = self._infer_num_input(vec.size)
        consumed = 0
        for name, start, shape in self._walk_slices(ni):
            n = 1
            for d in shape:
                n *= d
            out[name] = vec[start:start + n].reshape(shape).copy()
            consumed += n
        if consumed != vec.size:
            raise MXNetError("packed parameter size %d does not match the "
                             "cell spec" % vec.size)
        return out

    def pack_weights(self, args):
        import numpy as _np

        from ..ndarray import array

        out = dict(args)
        w0 = out["%sl0_i2h%s_weight" % (self._prefix, self._gate_names[0])]
        ni = w0.shape[1]
        h, b, m = self._num_hidden, len(self._directions), self._num_gates
        total = (ni + h + 2) * h * m * b + \
            (self._num_layers - 1) * m * h * (h + b * h + 2) * b
        # assemble host-side, one device upload at the end
        flat = _np.zeros((total,), dtype=_np.float32)
        for name, start, shape in self._walk_slices(ni):
            piece = out.pop(name)
            piece = piece.asnumpy() if hasattr(piece, "asnumpy") \
                else _np.asarray(piece)
            flat[start:start + piece.size] = piece.reshape(-1)
        out[self._parameter.name] = array(flat, ctx=w0.context,
                                          dtype=w0.dtype)
        return out

    # -- graph building ----------------------------------------------------
    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell has no per-step form; use unroll() "
                         "or unfuse()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        t_axis = layout.find("T")
        if not isinstance(inputs, symbol.Symbol):
            inputs = _as_merged(list(inputs), t_axis)
        if t_axis == 1:  # RNN op is time-major
            inputs = symbol.swapaxes(inputs, dim1=0, dim2=1)
        states = begin_state if begin_state is not None else \
            self.begin_state()
        state_kw = {"state": states[0]}
        if self._mode == "lstm":
            state_kw["state_cell"] = states[1]
        rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional,
                         p=self._dropout,
                         state_outputs=self._get_next_state,
                         mode=self._mode, name=self._prefix + "rnn",
                         **state_kw)
        if not self._get_next_state:
            outputs, out_states = rnn, []
        else:
            n_state = 2 if self._mode == "lstm" else 1
            outputs = rnn[0]
            out_states = [rnn[1 + i] for i in range(n_state)]
            for s in out_states:
                s._set_attr(__layout__="LNC")
        if t_axis == 1:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        outputs = _shape_outputs(outputs, length, layout, merge_outputs)
        return outputs, out_states

    def unfuse(self):
        """Equivalent stack of single-layer cells sharing the unpacked
        naming scheme (reference: FusedRNNCell.unfuse)."""
        make = {
            "rnn_relu": lambda pre: RNNCell(self._num_hidden,
                                            activation="relu", prefix=pre),
            "rnn_tanh": lambda pre: RNNCell(self._num_hidden,
                                            activation="tanh", prefix=pre),
            "lstm": lambda pre: LSTMCell(self._num_hidden, prefix=pre),
            "gru": lambda pre: GRUCell(self._num_hidden, prefix=pre),
        }[self._mode]
        stack = SequentialRNNCell()
        for layer in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make("%sl%d_" % (self._prefix, layer)),
                    make("%sr%d_" % (self._prefix, layer)),
                    output_prefix="%sbi_l%d_" % (self._prefix, layer)))
            else:
                stack.add(make("%sl%d_" % (self._prefix, layer)))
            if self._dropout > 0 and layer != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_"
                                      % (self._prefix, layer)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Vertical stack of cells (reference: SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            if not cell._own_params:
                raise MXNetError("give params to the stack or to the "
                                 "child cells, not both")
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return _sum_states(self._cells, "state_info")

    def begin_state(self, **kwargs):
        if self._modified:
            raise MXNetError("request begin_state from the modifier cell")
        return _sum_states(self._cells, "begin_state", **kwargs)

    def unpack_weights(self, args):
        return _chain_dicts(self._cells, "unpack_weights", args)

    def pack_weights(self, args):
        return _chain_dicts(self._cells, "pack_weights", args)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        pos = 0
        for cell in self._cells:
            if isinstance(cell, BidirectionalCell):
                raise MXNetError("BidirectionalCell cannot be stepped "
                                 "inside a stack; use unroll")
            n = len(cell.state_info)
            inputs, sub = cell(inputs, states[pos:pos + n])
            pos += n
            next_states.extend(sub)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        states = begin_state if begin_state is not None else \
            self.begin_state()
        pos = 0
        next_states = []
        last = len(self._cells) - 1
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            inputs, sub = cell.unroll(
                length, inputs=inputs, begin_state=states[pos:pos + n],
                layout=layout,
                merge_outputs=merge_outputs if i == last else None)
            pos += n
            next_states.extend(sub)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Stateless dropout-on-input cell (reference: DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        if not isinstance(dropout, (int, float)):
            raise MXNetError("dropout probability must be numeric")
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if isinstance(inputs, symbol.Symbol) and merge_outputs is not False:
            # dropout is elementwise: apply once to the merged sequence
            return self(inputs, [])
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs)


class ModifierCell(BaseRNNCell):
    """Wraps a cell and alters its stepping; parameters stay with the
    base cell (reference: ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        if self._modified:
            raise MXNetError("request begin_state from the outermost "
                             "modifier cell")
        self.base_cell._modified = False
        try:
            return self.base_cell.begin_state(func=func, **kwargs)
        finally:
            self.base_cell._modified = True

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout: randomly hold previous outputs/states
    (reference: ZoneoutCell; Krueger et al. 2016)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        if isinstance(base_cell, FusedRNNCell):
            raise MXNetError("unfuse() the cell before applying zoneout")
        if isinstance(base_cell, BidirectionalCell):
            raise MXNetError("apply zoneout to the cells inside the "
                             "BidirectionalCell instead")
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)

        def held(p, new, old):
            keep = symbol.Dropout(symbol.ones_like(new), p=p)
            return symbol.where(keep, new, old)

        if self.zoneout_outputs > 0.0:
            prev = self._prev_output
            if prev is None:
                prev = symbol.zeros(shape=(1, 1))
            out = held(self.zoneout_outputs, out, prev)
        if self.zoneout_states > 0.0:
            next_states = [held(self.zoneout_states, n, o)
                           for n, o in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class ResidualCell(ModifierCell):
    """output = base(output) + input (reference: ResidualCell;
    Wu et al. 2016)."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        out = symbol.elemwise_add(out, inputs,
                                  name="%s_plus_residual" % out.name)
        return out, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        try:
            outputs, states = self.base_cell.unroll(
                length, inputs=inputs, begin_state=begin_state,
                layout=layout, merge_outputs=merge_outputs)
        finally:
            self.base_cell._modified = True
        merged = isinstance(outputs, symbol.Symbol) \
            if merge_outputs is None else merge_outputs
        t_axis = layout.find("T")
        if merged:
            if not isinstance(inputs, symbol.Symbol):
                inputs = _as_merged(list(inputs), t_axis)
            outputs = symbol.elemwise_add(
                outputs, inputs, name="%s_plus_residual" % outputs.name)
        else:
            steps, _ = _as_steps(inputs, length, layout)
            outputs = [symbol.elemwise_add(o, x,
                                           name="%s_plus_residual" % o.name)
                       for o, x in zip(outputs, steps)]
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Runs one cell forward and one backward over the sequence and
    concatenates per-step outputs (reference: BidirectionalCell).

    Divergence note: unroll returns the states as one flat list
    ``l_states + r_states`` (matching begin_state's layout) rather than
    the reference's nested ``[l_states, r_states]``."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            if not (l_cell._own_params and r_cell._own_params):
                raise MXNetError("give params to the BidirectionalCell or "
                                 "to the child cells, not both")
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    @property
    def state_info(self):
        return _sum_states(self._cells, "state_info")

    def begin_state(self, **kwargs):
        if self._modified:
            raise MXNetError("request begin_state from the modifier cell")
        return _sum_states(self._cells, "begin_state", **kwargs)

    def unpack_weights(self, args):
        return _chain_dicts(self._cells, "unpack_weights", args)

    def pack_weights(self, args):
        return _chain_dicts(self._cells, "pack_weights", args)

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell sees the whole sequence; "
                         "use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        steps, t_axis = _as_steps(inputs, length, layout)
        states = begin_state if begin_state is not None else \
            self.begin_state()
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_out, l_states = l_cell.unroll(length, inputs=steps,
                                        begin_state=states[:n_l],
                                        layout=layout, merge_outputs=False)
        r_out, r_states = r_cell.unroll(length,
                                        inputs=list(reversed(steps)),
                                        begin_state=states[n_l:],
                                        layout=layout, merge_outputs=False)
        r_out = list(reversed(r_out))
        outputs = [symbol.Concat(lo, ro, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (lo, ro) in enumerate(zip(l_out, r_out))]
        if merge_outputs:
            outputs = _as_merged(outputs, t_axis)
        return outputs, l_states + r_states


class BaseConvRNNCell(BaseRNNCell):
    """Convolutional recurrence: i2h and h2h are Convolutions over
    spatial state maps (reference: rnn_cell.py BaseConvRNNCell).  The
    h2h kernel must be odd so SAME padding preserves the state shape."""

    def __init__(self, input_shape, num_hidden, h2h_kernel, h2h_dilate,
                 i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                 i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, activation,
                 prefix="", params=None, conv_layout="NCHW"):
        super().__init__(prefix=prefix, params=params)
        if h2h_kernel[0] % 2 != 1 or h2h_kernel[1] % 2 != 1:
            raise MXNetError("h2h_kernel must be odd (SAME padding), got %s"
                             % (h2h_kernel,))
        self._h2h_kernel = tuple(h2h_kernel)
        self._h2h_dilate = tuple(h2h_dilate)
        self._h2h_pad = (h2h_dilate[0] * (h2h_kernel[0] - 1) // 2,
                         h2h_dilate[1] * (h2h_kernel[1] - 1) // 2)
        self._i2h_kernel = tuple(i2h_kernel)
        self._i2h_stride = tuple(i2h_stride)
        self._i2h_pad = tuple(i2h_pad)
        self._i2h_dilate = tuple(i2h_dilate)
        self._num_hidden = num_hidden
        self._input_shape = tuple(input_shape)
        self._conv_layout = conv_layout
        self._activation = activation

        # state spatial shape comes from the i2h conv on one timestep
        probe = symbol.Convolution(
            symbol.Variable("data"), num_filter=num_hidden,
            kernel=self._i2h_kernel, stride=self._i2h_stride,
            pad=self._i2h_pad, dilate=self._i2h_dilate, layout=conv_layout)
        _, out_shapes, _ = probe.infer_shape(data=self._input_shape)
        self._state_shape = (0,) + tuple(out_shapes[0][1:])

        p = self.params
        self._w = {
            "i2h_weight": p.get("i2h_weight", init=i2h_weight_initializer),
            "h2h_weight": p.get("h2h_weight", init=h2h_weight_initializer),
            "i2h_bias": p.get("i2h_bias", init=i2h_bias_initializer),
            "h2h_bias": p.get("h2h_bias", init=h2h_bias_initializer),
        }

    @property
    def _num_gates(self):
        return len(self._gate_names)

    @property
    def state_info(self):
        return [{"shape": self._state_shape,
                 "__layout__": self._conv_layout}]

    def _conv_projections(self, inputs, h_prev, step_name):
        n = self._num_hidden * self._num_gates
        i2h = symbol.Convolution(
            data=inputs, weight=self._w["i2h_weight"],
            bias=self._w["i2h_bias"], num_filter=n,
            kernel=self._i2h_kernel, stride=self._i2h_stride,
            pad=self._i2h_pad, dilate=self._i2h_dilate,
            layout=self._conv_layout, name="%si2h" % step_name)
        h2h = symbol.Convolution(
            data=h_prev, weight=self._w["h2h_weight"],
            bias=self._w["h2h_bias"], num_filter=n,
            kernel=self._h2h_kernel, stride=(1, 1), pad=self._h2h_pad,
            dilate=self._h2h_dilate, layout=self._conv_layout,
            name="%sh2h" % step_name)
        return i2h, h2h


def _leaky(x, name=None):
    return symbol.LeakyReLU(x, act_type="leaky", slope=0.2, name=name)


class ConvRNNCell(BaseConvRNNCell):
    """h' = act(conv(x) + conv(h)) (reference: rnn_cell.py ConvRNNCell)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 activation=_leaky, prefix="ConvRNN_", params=None,
                 conv_layout="NCHW"):
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         activation, prefix, params, conv_layout)

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        name = self._step_name()
        i2h, h2h = self._conv_projections(inputs, states[0], name)
        out = self._activate(i2h + h2h, self._activation,
                             name="%sout" % name)
        return out, [out]


class ConvLSTMCell(BaseConvRNNCell):
    """Convolutional LSTM (reference: rnn_cell.py ConvLSTMCell;
    Xingjian et al. 2015)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 activation=_leaky, prefix="ConvLSTM_", params=None,
                 conv_layout="NCHW"):
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         activation, prefix, params, conv_layout)

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    @property
    def state_info(self):
        return [{"shape": self._state_shape,
                 "__layout__": self._conv_layout},
                {"shape": self._state_shape,
                 "__layout__": self._conv_layout}]

    def __call__(self, inputs, states):
        name = self._step_name()
        i2h, h2h = self._conv_projections(inputs, states[0], name)
        c_axis = self._conv_layout.find("C")
        g_i, g_f, g_c, g_o = symbol.SliceChannel(
            i2h + h2h, num_outputs=4, axis=c_axis, name="%sslice" % name)
        i = symbol.Activation(g_i, act_type="sigmoid", name="%si" % name)
        f = symbol.Activation(g_f, act_type="sigmoid", name="%sf" % name)
        c_tilde = self._activate(g_c, self._activation, name="%sc" % name)
        o = symbol.Activation(g_o, act_type="sigmoid", name="%so" % name)
        next_c = symbol.elemwise_add(f * states[1], i * c_tilde,
                                     name="%sstate" % name)
        next_h = symbol.elemwise_mul(
            o, self._activate(next_c, self._activation),
            name="%sout" % name)
        return next_h, [next_h, next_c]


class ConvGRUCell(BaseConvRNNCell):
    """Convolutional GRU (reference: rnn_cell.py ConvGRUCell)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 activation=_leaky, prefix="ConvGRU_", params=None,
                 conv_layout="NCHW"):
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         activation, prefix, params, conv_layout)

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        name = self._step_name()
        h_prev = states[0]
        i2h, h2h = self._conv_projections(inputs, h_prev, name)
        c_axis = self._conv_layout.find("C")
        xr, xz, xn = symbol.SliceChannel(i2h, num_outputs=3, axis=c_axis,
                                         name="%s_i2h_slice" % name)
        hr, hz, hn = symbol.SliceChannel(h2h, num_outputs=3, axis=c_axis,
                                         name="%s_h2h_slice" % name)
        r = symbol.Activation(xr + hr, act_type="sigmoid",
                              name="%s_r_act" % name)
        z = symbol.Activation(xz + hz, act_type="sigmoid",
                              name="%s_z_act" % name)
        cand = self._activate(xn + r * hn, self._activation,
                              name="%s_h_act" % name)
        next_h = symbol.elemwise_add((1.0 - z) * cand, z * h_prev,
                                     name="%sout" % name)
        return next_h, [next_h]
