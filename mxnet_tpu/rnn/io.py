"""Bucketed sequence data (reference: python/mxnet/rnn/io.py).

``BucketSentenceIter`` groups variable-length sentences into a small
set of fixed lengths.  On TPU this is the shape-bucketing strategy:
each bucket length is one static-shape XLA executable (the
BucketingModule keeps one compiled module per bucket key), so a corpus
runs with a handful of compiles instead of per-length recompilation.
"""

from __future__ import annotations

import bisect
import logging
import random

import numpy as np

from ..io import DataBatch, DataDesc, DataIter
from .. import ndarray

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Map token sentences to int ids, growing the vocabulary as needed
    (reference: io.py encode_sentences)."""
    grow = vocab is None
    if grow:
        vocab = {invalid_key: invalid_label}
    next_id = start_label
    encoded = []
    for sent in sentences:
        ids = []
        for token in sent:
            if token not in vocab:
                if not (grow or unknown_token):
                    raise ValueError("unknown token %r with a frozen "
                                     "vocabulary" % (token,))
                if unknown_token:
                    token = unknown_token
                if token not in vocab:
                    if next_id == invalid_label:
                        next_id += 1
                    vocab[token] = next_id
                    next_id += 1
            ids.append(vocab[token])
        encoded.append(ids)
    return encoded, vocab


class BucketSentenceIter(DataIter):
    """Language-model iterator: each batch is one bucket's fixed length,
    label = data shifted left by one token
    (reference: io.py BucketSentenceIter).

    Yields DataBatch with ``bucket_key`` set, for BucketingModule.
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NT"):
        super().__init__(batch_size=batch_size)
        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.major_axis = layout.find("N")
        if self.major_axis not in (0, 1):
            raise ValueError("layout must be 'NT' (batch-major) or 'TN' "
                             "(time-major), got %r" % (layout,))

        if not buckets:
            # default buckets: every length with enough sentences to fill
            # at least one batch
            counts = np.bincount([len(s) for s in sentences])
            buckets = [length for length, c in enumerate(counts)
                       if c >= batch_size]
        buckets = sorted(buckets)

        per_bucket = [[] for _ in buckets]
        discarded = 0
        for sent in sentences:
            slot = bisect.bisect_left(buckets, len(sent))
            if slot == len(buckets):
                discarded += 1
                continue
            row = np.full((buckets[slot],), invalid_label, dtype=dtype)
            row[:len(sent)] = sent
            per_bucket[slot].append(row)
        if discarded:
            logging.warning("BucketSentenceIter: discarded %d sentences "
                            "longer than the largest bucket", discarded)
        # drop empty buckets
        kept = [(b, rows) for b, rows in zip(buckets, per_bucket) if rows]
        self.buckets = [b for b, _ in kept]
        self.data = [np.asarray(rows, dtype=dtype) for _, rows in kept]
        if not self.buckets:
            raise ValueError("no bucket holds a full batch; lower "
                             "batch_size or pass explicit buckets")
        self.default_bucket_key = max(self.buckets)

        shape = (batch_size, self.default_bucket_key) \
            if self.major_axis == 0 else \
            (self.default_bucket_key, batch_size)
        self.provide_data = [DataDesc(name=data_name, shape=shape,
                                      layout=layout)]
        self.provide_label = [DataDesc(name=label_name, shape=shape,
                                       layout=layout)]

        self.idx = []
        self.nddata = []
        self.ndlabel = []
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        # shuffle batch order across buckets AND rows within buckets
        self.idx = [(i, j) for i, rows in enumerate(self.data)
                    for j in range(0, len(rows) - self.batch_size + 1,
                                   self.batch_size)]
        random.shuffle(self.idx)
        self.nddata, self.ndlabel = [], []
        for rows in self.data:
            np.random.shuffle(rows)
            label = np.full_like(rows, self.invalid_label)
            label[:, :-1] = rows[:, 1:]
            self.nddata.append(ndarray.array(rows, dtype=self.dtype))
            self.ndlabel.append(ndarray.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        if self.major_axis == 1:
            data, label = data.T, label.T
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[i],
            provide_data=[DataDesc(name=self.data_name, shape=data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(name=self.label_name, shape=label.shape,
                                    layout=self.layout)])
