"""Symbolic RNN package (reference: python/mxnet/rnn/)."""

from .rnn_cell import (BaseConvRNNCell, BaseRNNCell, BidirectionalCell,  # noqa: F401
                       ConvGRUCell, ConvLSTMCell, ConvRNNCell,
                       DropoutCell, FusedRNNCell, GRUCell, LSTMCell,
                       ModifierCell, RNNCell, RNNParams, ResidualCell,
                       SequentialRNNCell, ZoneoutCell)
from .rnn import (do_rnn_checkpoint, load_rnn_checkpoint, rnn_unroll,  # noqa: F401
                  save_rnn_checkpoint)
from .io import BucketSentenceIter, encode_sentences  # noqa: F401
