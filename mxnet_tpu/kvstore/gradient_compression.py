"""2-bit gradient compression with error feedback.

Reference: src/kvstore/gradient_compression.{h,cc,cu} (kTwoBit:38) —
values are quantized to {-threshold, 0, +threshold}; the quantization
residual is kept worker-side and added to the next gradient (error
feedback), so compression error does not accumulate.

TPU note: the actual bit-packing of the reference (16 2-bit values per
float) matters for ZMQ wire size; here the "wire" is ICI/DCN handled by
XLA, so we keep the *numerics* (quantize→dequantize with residual) in
one fused jitted kernel — int8/fp8 grad allreduce is the production
path (SURVEY.md §2.3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def _two_bit_round_trip(grad, residual, threshold):
    g = grad + residual
    pos = (g >= threshold).astype(grad.dtype)
    neg = (g <= -threshold).astype(grad.dtype)
    out = pos * threshold - neg * threshold
    new_residual = g - out
    return out, new_residual


class GradientCompression:
    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residuals = {}

    def get_params(self):
        return {"type": "2bit", "threshold": self.threshold}

    def compress_decompress(self, key, grad):
        """Quantize+dequantize with per-key residual (error feedback)."""
        from ..ndarray import NDArray

        res = self._residuals.get(key)
        if res is None:
            res = jnp.zeros(grad.shape, dtype=grad.dtype)
        out, new_res = _two_bit_round_trip(grad._data, res,
                                           jnp.asarray(self.threshold,
                                                       dtype=grad.dtype))
        self._residuals[key] = new_res
        return NDArray(out, grad._ctx)
