"""KVStore — the parameter-synchronisation façade.

Reference: include/mxnet/kvstore.h:59-442, src/kvstore/kvstore_local.h:69,
comm.h (CommCPU/CommDevice/CommDeviceTree), kvstore_nccl.h, kvstore_dist.h.

TPU-native design: there is no parameter server and no NCCL — reduction
is either trivial (single process: sum the pushed list, one fused XLA
kernel) or an ``lax.psum`` over the device mesh inside the jitted train
step (kvstore type 'tpu'; see mxnet_tpu/parallel/).  The KVStore *API*
(init/push/pull/set_optimizer/rank/num_workers/barrier) is kept verbatim
so Module/Trainer code written against the reference runs unchanged:

- 'local' / 'device' / 'nccl' / 'tpu'  → in-process store; push sums
  across the per-device gradient copies (the reference's Comm::Reduce,
  comm.h:57) and runs the updater if set.
- 'dist_sync' → multi-process via ``jax.distributed`` when launched
  under tools/launch.py (DMLC_* env parity); cross-worker reduction uses
  a host-level allreduce over the process group.  On a single process it
  degrades to 'local' with num_workers=1.
- 'dist_async' → true parameter-server mode: pushes apply immediately on
  host-side PS processes (kvstore/ps.py, launched by
  ``launch.py -s N``), the reference's Hogwild-style async semantics.
"""

from __future__ import annotations

import json
import os
import pickle

import numpy as _np

from .. import profiler as _profiler
from .. import runtime_stats as _rts
from .. import stepstats as _stepstats
from ..base import MXNetError
from ..ndarray import NDArray, array, zeros
from ..optimizer import Optimizer, get_updater
from .gradient_compression import GradientCompression

__all__ = ["KVStore", "create"]


def create(name="local"):
    """Create a KVStore (reference: kvstore.cc:40 factory)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device", "nccl", "tpu"):
        return KVStore(name)
    if name in ("dist", "dist_sync", "dist_sync_device", "dist_device_sync"):
        return DistKVStore(name)
    if name == "dist_async":
        return DistAsyncKVStore(name)
    raise MXNetError("unknown KVStore type %r" % name)


class KVStore:
    """Single-process store (reference: KVStoreLocal, kvstore_local.h:69)."""

    def __init__(self, type_name="local"):
        self._type = type_name
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._str_keys = set()

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # ------------------------------------------------------------- core
    def _canon(self, key):
        return key

    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Reduce pushed values per key; apply updater if set
        (reference: KVStoreLocal::PushImpl → Comm::Reduce comm.h:57)."""
        _rts.inc("kvstore_pushes")
        # step-anatomy kvstore phase (base + dist backends all route
        # through this wrapper): a container window, so the add_n
        # reduce dispatch inside stays in dispatch_warm (stepstats.py)
        ss_on = _stepstats._state["on"]
        if ss_on:
            ss_tok = _stepstats.begin()
        with _profiler.span("kvstore:push", "kvstore",
                            args={"type": self._type}
                            if _profiler._state["running"] else None):
            self._push_impl(key, value, priority)
        if ss_on:
            _stepstats.end("kvstore", ss_tok)

    def _push_impl(self, key, value, priority):
        keys, values = _key_value_list(key, value)
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            merged = vlist[0]
            if len(vlist) > 1:
                from ..ndarray import imperative_invoke

                merged = imperative_invoke("add_n", list(vlist), {})[0]
            else:
                merged = merged.copy()
            if self._compression is not None:
                merged = self._compression.compress_decompress(k, merged)
            if self._updater is not None:
                self._updater(_key_int(k), merged, self._store[k])
            else:
                # reference semantics (kvstore_local.h:213): without an
                # updater the store holds the REDUCED value, replacing —
                # this is what makes Trainer's push(grads)/pull(grads)
                # return the cross-device gradient sum
                self._store[k] = merged

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value (reference: Comm::Broadcast comm.h:62)."""
        assert out is not None
        _rts.inc("kvstore_pulls")
        ss_on = _stepstats._state["on"]
        if ss_on:
            ss_tok = _stepstats.begin()
        with _profiler.span("kvstore:pull", "kvstore",
                            args={"type": self._type}
                            if _profiler._state["running"] else None):
            self._pull_impl(key, out, priority, ignore_sparse)
        if ss_on:
            _stepstats.end("kvstore", ss_tok)

    def _pull_impl(self, key, out, priority, ignore_sparse):
        keys, outs = _key_value_list(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            for o in olist:
                self._store[k].copyto(o)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull selected rows (reference: PullRowSparse kvstore.h:232).

        Rows outside row_ids are zeroed in the output — dense emulation of
        the row_sparse pull contract."""
        assert out is not None and row_ids is not None
        keys, outs = _key_value_list(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(outs[0])
        for k, olist in zip(keys, outs):
            full = self._store[k]
            for o, rid in zip(olist, row_ids if isinstance(row_ids, list)
                              else [row_ids] * len(olist)):
                idx = rid.asnumpy().astype(_np.int64) if isinstance(rid, NDArray) \
                    else _np.asarray(rid, dtype=_np.int64)
                dense = _np.zeros(full.shape, dtype=full.asnumpy().dtype)
                src = full.asnumpy()
                dense[idx] = src[idx]
                o[:] = dense

    # ------------------------------------------------------------- config
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """reference: kvstore.py set_optimizer → server-side optimizer;
        here the 'server' is in-process."""
        if not isinstance(optimizer, Optimizer):
            raise TypeError("optimizer must be an Optimizer")
        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error feedback
        (reference: gradient_compression.h:52)."""
        params = dict(compression_params)
        ctype = params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError("only 2bit compression is supported (parity)")
        self._compression = GradientCompression(
            threshold=float(params.get("threshold", 0.5)))

    # ------------------------------------------------------------- dist API
    def barrier(self):
        pass

    def _send_command_to_servers(self, head, body):
        """reference: MXKVStoreSendCommmandToServers, a silent no-op on
        non-dist stores.  We keep the no-op for parity (reference scripts
        issue server commands unconditionally) but warn, so a 'server
        profiling' request that goes nowhere doesn't surface only as a
        mysteriously missing trace file later."""
        import warnings

        warnings.warn(
            "kvstore type %r has no server processes to command — the "
            "request is ignored (server commands need 'dist_async' under "
            "tools/launch.py -s N)" % self._type, stacklevel=2)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "updater is not set"
        from ..checkpoint import atomic_write

        with atomic_write(fname) as tmp:
            with open(tmp, "wb") as f:
                f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "updater is not set"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


class DistKVStore(KVStore):
    """Multi-process synchronous store over jax.distributed.

    Reference: kvstore_dist.h:44 (worker) + kvstore_dist_server.h:155.
    The ps-lite push/pull wire protocol is replaced by collective
    reduction across the jax process group (DCN); server-side optimizer
    semantics (sync aggregation of num_workers pushes before update,
    kvstore_dist_server.h:346) are preserved by reducing first, then
    applying the updater once per pushed key.
    """

    def __init__(self, type_name):
        super().__init__(type_name)
        self._rank = int(os.environ.get("DMLC_WORKER_ID",
                                        os.environ.get("JAX_PROCESS_ID", 0)))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", 1))
        self._group = None
        if self._num_workers > 1:
            self._init_process_group()

    def _init_process_group(self):
        import jax

        # normally already joined at import (mxnet_tpu._maybe_init_distributed
        # reads the same DMLC_* contract); handle direct construction too.
        # Feature-detect is_initialized: some jax builds ship
        # jax.distributed without it
        is_init = getattr(jax.distributed, "is_initialized", None)
        if is_init is not None and is_init():
            self._group = True
            return
        coord = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
        try:
            jax.distributed.initialize(
                coordinator_address="%s:%s" % (coord, port),
                num_processes=self._num_workers,
                process_id=self._rank)
            self._group = True
        except Exception as e:
            raise MXNetError("dist kvstore init failed: %s" % e)

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def _push_impl(self, key, value, priority):
        keys, values = _key_value_list(key, value)
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            merged = vlist[0]
            if len(vlist) > 1:
                from ..ndarray import imperative_invoke

                merged = imperative_invoke("add_n", list(vlist), {})[0]
            else:
                merged = merged.copy()
            if self._compression is not None:
                # per-worker quantize BEFORE aggregation (reference:
                # PushCompressed kvstore_dist.h:378 — each worker sends
                # its own quantized gradient; residual stays worker-side)
                merged = self._compression.compress_decompress(k, merged)
            if self._num_workers > 1:
                merged = self._allreduce(merged)
            if self._updater is not None:
                self._updater(_key_int(k), merged, self._store[k])
            else:
                # replace with the reduced value (reference:
                # kvstore_dist_server.h:360 CopyFromTo(merged, stored))
                self._store[k] = merged

    def init(self, key, value):
        """Init + broadcast rank 0's value so every replica starts from
        identical weights (reference: dist kv.init stores on the server
        once; workers pull the same tensor, kvstore_dist.h InitImpl)."""
        super().init(key, value)
        if self._num_workers > 1:
            keys, _ = _key_value(key, value)
            for k in keys:
                v = self._store[k]
                src = v if self._rank == 0 else \
                    NDArray(v._data * 0, v._ctx)
                self._store[k] = self._allreduce(src)

    def _allreduce(self, arr):
        """Cross-process sum over DCN via a tiny jitted psum."""
        import jax

        from ..parallel import host_allreduce

        return NDArray(host_allreduce(arr._data), arr._ctx)

    def barrier(self):
        if self._num_workers > 1:
            import jax

            # a zero-byte allreduce doubles as a barrier
            self._allreduce(array(_np.zeros(1, dtype=_np.float32)))


class DistAsyncKVStore(KVStore):
    """`dist_async`: true parameter-server mode over the host-side PS
    (`kvstore/ps.py`).

    Reference semantics (kvstore_dist_server.h async branch): each
    worker's push is applied to the server weights IMMEDIATELY — no
    cross-worker aggregation barrier — and pull returns whatever the
    server currently holds, so workers run at their own pace with stale
    weights (Hogwild-style).  The server runs the optimizer; workers
    ship it once via set_optimizer (reference: kvstore.py
    _send_command_to_servers).
    """

    def __init__(self, type_name="dist_async"):
        super().__init__(type_name)
        self._rank = int(os.environ.get("DMLC_WORKER_ID", 0))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", 1))
        launched = "DMLC_ROLE" in os.environ or \
            "MXTPU_PS_PORTS" in os.environ
        if not launched and self._num_workers == 1:
            # no launcher env: degrade to an in-process store like the
            # other dist types (a notebook `mx.kv.create('dist_async')`
            # must not dial a nonexistent server)
            self._client = None
            return
        if int(os.environ.get("DMLC_NUM_SERVER", "1")) == 0:
            # launched with -n but not -s: without this check the client
            # would dial the jax.distributed coordinator port (which IS
            # listening) and hang in recv instead of failing fast
            raise MXNetError(
                "dist_async needs parameter-server processes — relaunch "
                "with `tools/launch.py -n %d -s <servers>`"
                % self._num_workers)
        from .ps import PSClient

        try:
            self._client = PSClient()
        except OSError as e:
            raise MXNetError(
                "dist_async needs parameter-server processes — start the "
                "job with `tools/launch.py -n <workers> -s <servers>` "
                "(%s)" % e)
        # diag-push cadence: MXNET_TPU_DIAG_PUSH=N>1 parks this rank's
        # diag snapshot on shard 0 every N pushes (N=1: on dump only)
        try:
            self._diag_push_every = int(
                os.environ.get("MXNET_TPU_DIAG_PUSH", "0") or 0)
        except ValueError:
            self._diag_push_every = 0
        self._diag_push_count = 0
        # register as the server-command channel (profiler forwarding,
        # diag push on dump) — the reference needs an explicit
        # set_kvstore_handle call; the TPU-native form self-registers
        # since a process has at most one dist store
        _profiler.set_kvstore_handle(self)
        if os.environ.get("MXNET_TPU_PROFILE") or \
                _profiler._state["running"]:
            # profiled run: estimate the worker→server clock offset now
            # so this rank's chrome trace can be merged onto the
            # cluster timeline (profiler.merge_traces)
            try:
                self.estimate_clock_offset()
            except Exception:
                pass  # telemetry must never block store construction

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def init(self, key, value):
        """Rank 0's value becomes the server copy (reference: InitImpl
        pushes init only from worker 0)."""
        if self._client is None:
            return super().init(key, value)
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if self._rank == 0:
                self._client.init(k, v.asnumpy())
        self.barrier()

    def _push_impl(self, key, value, priority):
        if self._client is None:
            return super()._push_impl(key, value, priority)
        keys, values = _key_value_list(key, value)
        for k, vlist in zip(keys, values):
            merged = vlist[0]
            if len(vlist) > 1:
                from ..ndarray import imperative_invoke

                merged = imperative_invoke("add_n", list(vlist), {})[0]
            if self._compression is not None:
                merged = self._compression.compress_decompress(k, merged)
            self._client.push(k, merged.asnumpy())
        if self._diag_push_every > 1:
            self._diag_push_count += 1
            if self._diag_push_count % self._diag_push_every == 0:
                try:
                    self.push_diag()
                except Exception:
                    pass  # interval telemetry must never fail a push

    def _pull_impl(self, key, out, priority, ignore_sparse):
        if self._client is None:
            return super()._pull_impl(key, out, priority, ignore_sparse)
        assert out is not None
        keys, outs = _key_value_list(key, out)
        for k, olist in zip(keys, outs):
            fetched = self._client.pull(k)
            for o in olist:
                o[:] = fetched

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if self._client is None:
            return super().row_sparse_pull(key, out, priority, row_ids)
        assert out is not None and row_ids is not None
        keys, outs = _key_value_list(key, out)
        for k, olist in zip(keys, outs):
            full = self._client.pull(k)
            rids = row_ids if isinstance(row_ids, list) \
                else [row_ids] * len(olist)
            for o, rid in zip(olist, rids):
                idx = rid.asnumpy().astype(_np.int64) \
                    if isinstance(rid, NDArray) \
                    else _np.asarray(rid, dtype=_np.int64)
                dense = _np.zeros_like(full)
                dense[idx] = full[idx]
                o[:] = dense

    def set_optimizer(self, optimizer):
        """Ship the optimizer to the servers; the update runs
        server-side (reference: server-side `Executor` running the
        pickled optimizer, kvstore_dist_server.h:95)."""
        import copy
        import pickle

        if self._client is None:
            return super().set_optimizer(optimizer)
        if not isinstance(optimizer, Optimizer):
            raise TypeError("optimizer must be an Optimizer")
        self._optimizer = optimizer
        if self._rank == 0:
            # strip param_dict before shipping: it holds live Parameters
            # whose pickling embeds full weight tensors — the server only
            # needs the per-index multipliers (reference: server gets the
            # optimizer string, not the weights)
            wire = copy.copy(optimizer)
            wire.param_dict = {}
            wire.lr_mult = dict(optimizer.lr_mult)
            wire.wd_mult = dict(optimizer.wd_mult)
            for idx, p in optimizer.param_dict.items():
                if getattr(p, "lr_mult", 1.0) != 1.0:
                    wire.lr_mult[idx] = p.lr_mult
                if getattr(p, "wd_mult", 1.0) != 1.0:
                    wire.wd_mult[idx] = p.wd_mult
            self._client.set_optimizer(
                pickle.dumps(wire, protocol=pickle.HIGHEST_PROTOCOL))
        self.barrier()

    def barrier(self):
        if self._client is not None:
            self._client.barrier()

    def _send_command_to_servers(self, head, body):
        """Generic controller channel (reference: ps-lite server commands
        — stop/set-optimizer/gradient-compression/profiler)."""
        if self._client is None:
            return super()._send_command_to_servers(head, body)  # warns
        self._client.send_command(head, body)

    def stop_servers(self):
        """Send the stop command (reference: scheduler 'stop' on
        finalize)."""
        if self._client is not None:
            self._client.stop_servers()
        # deregister the server-command channel: an atexit diag dump
        # after shutdown must not try to push through a stopped store
        if _profiler._kvstore_handle is self:
            _profiler.set_kvstore_handle(None)

    # --------------------------------------------- distributed telemetry
    def server_stats(self):
        """Every PS shard's server-side metrics (per-key bytes in/out +
        applied-mutation versions, per-peer request counts, apply/handle
        latency histograms, queue depth, accepted connections, plus the
        ``dedup`` exactly-once table and ``durability`` checkpoint
        state) — the ``stats`` command (docs/OBSERVABILITY.md
        "Distributed telemetry").  Empty list on a degraded in-process
        store."""
        if self._client is None:
            return []
        return self._client.server_stats()

    def checkpoint_servers(self):
        """Ask every PS shard to commit its durable store snapshot NOW
        (the reserved ``ckpt`` command head): one
        ``{"enabled", "step", "path"}`` dict per shard — ``enabled`` is
        False for servers running without ``MXNET_TPU_PS_CKPT``
        (docs/CHECKPOINTING.md "Server-side durability").  Empty list
        on a degraded in-process store."""
        if self._client is None:
            return []
        return self._client.checkpoint_shards()

    def push_diag(self, top=20):
        """Park this rank's ``runtime_stats.diag_snapshot()`` on PS
        shard 0 (``diag_put``) so the operator can pull every rank's
        dump from one place.  Returns False on a degraded store."""
        if self._client is None:
            return False
        from .. import runtime_stats as _rts2

        snap = _rts2.diag_snapshot(top=top)
        ident = snap.get("identity") or {}
        # the rank key travels on its own line ahead of the payload so
        # the server never JSON-parses the (potentially large) dump
        key = "%s %s" % (ident.get("role", "worker"),
                         ident.get("rank", "?"))
        self._client.command_shard(
            0, "diag_put",
            key + "\n" + json.dumps(snap, default=repr))
        return True

    def cluster_diag(self):
        """Fetch every rank's parked diag dump from shard 0:
        ``{"worker 3": dump-dict, ...}`` — feed the values to
        ``runtime_stats.cluster_report`` for the merged view."""
        if self._client is None:
            return {}
        raw = self._client.command_shard(0, "diag_get") or {}
        return {k: json.loads(v) for k, v in raw.items()}

    def request_restart(self, rank=None, reason=""):
        """Park a supervised-relaunch request for ``rank`` (default:
        THIS worker) on PS shard 0 — the reserved ``restart_rank``
        head the ``tools/launch.py --supervise`` loop polls and honors
        (the autopilot's kv-RTT straggler reflex).  Returns the
        shard's ack dict, or False on a degraded in-process store."""
        if self._client is None:
            return False
        return self._client.request_restart(
            self.rank if rank is None else int(rank), reason=reason)

    def estimate_clock_offset(self, samples=5):
        """Ping shard 0 and register this process's wall-clock offset
        with the profiler (``set_clock_offset``) so per-rank chrome
        traces merge onto one cluster timeline.  Returns the offset in
        seconds (None on a degraded store)."""
        if self._client is None:
            return None
        offset, _rtt = self._client.ping(0, samples=samples)
        _profiler.set_clock_offset(offset)
        return offset


def _key_value(key, value):
    """Normalize (key(s), value(s)) to parallel lists."""
    if isinstance(key, (str, int)):
        return [key], [value if isinstance(value, NDArray) else value]
    assert len(key) == len(value)
    return list(key), list(value)


def _key_value_list(key, value):
    """Normalize to (keys, list-of-NDArray-lists)."""
    if isinstance(key, (str, int)):
        vlist = value if isinstance(value, (list, tuple)) else [value]
        return [key], [list(vlist)]
    out_keys = list(key)
    out_vals = []
    for v in value:
        out_vals.append(list(v) if isinstance(v, (list, tuple)) else [v])
    return out_keys, out_vals


def _key_int(key):
    if isinstance(key, int):
        return key
    try:
        return int(key)
    except (TypeError, ValueError):
        return key
