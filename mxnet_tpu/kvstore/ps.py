"""Host-side parameter server: the `dist_async` backend.

Reference: ps-lite worker/server (`src/kvstore/kvstore_dist.h:44`,
`kvstore_dist_server.h:155`).  The reference's async mode applies each
worker's push to the server-side weights IMMEDIATELY (no aggregation
barrier — `DataHandleEx` async path, `kvstore_dist_server.h:325`), and
the server runs the optimizer on CPU.  That is already a host-side
service, so the TPU-native form keeps the same shape: a TCP server
process holding the weights, applying the (pickled, worker-provided)
optimizer per push, with workers pulling the latest weights.  Device
compute (the jitted train step) is untouched — async staleness is a
coordination policy, not a device concern.

Sharding: keys are distributed across `num_servers` processes by
`int_key % num_servers` (the analog of ps-lite's `EncodeDefaultKey`
server assignment, `kvstore_dist.h:245`).  Server addresses come from
`MXTPU_PS_PORTS` (comma-separated, set by `tools/launch.py`) with
`DMLC_PS_ROOT_URI` as the host, falling back to
`DMLC_PS_ROOT_PORT` for a single server.

Wire format: 8-byte big-endian length + restricted pickle.  Like
ps-lite's ZMQ transport this is an unauthenticated intra-cluster
protocol (only run it on trusted networks; the launcher binds loopback
by default) — but data messages are decoded with an unpickler that
admits only builtins and numpy array/dtype reconstruction, so a rogue
peer cannot execute code via the data plane.  The ``set_optimizer``
blob (r3) is decoded by an ALLOWLISTED unpickler that admits only
classes from this framework's optimizer/lr_scheduler modules plus the
numpy reconstructors — the worker still ships its configured Optimizer
instance like the reference (python/mxnet/kvstore.py set_optimizer),
but a rogue peer can no longer reach arbitrary globals through it.

Fault tolerance (PR 6): worker→server RPCs retry transient transport
errors with bounded exponential backoff + reconnect (``PSClient._call``;
``MXNET_TPU_KV_RETRIES``/``MXNET_TPU_KV_RETRY_BACKOFF``), server-side
per-connection errors are logged rate-limited with the peer address
instead of silently swallowed, and ``MXNET_TPU_FAULT`` injects
deterministic failures (drop/delay/refuse connections, drop replies,
kill/restart the server after N messages) so all of it is testable —
docs/CHECKPOINTING.md "Fault injection".

Self-healing (PR 9, docs/CHECKPOINTING.md "Server-side durability"):

- **Durable shards.**  ``MXNET_TPU_PS_CKPT=<dir>`` makes each shard
  persist its store (key → value + per-key applied-mutation version),
  the worker-shipped optimizer blob, the exactly-once dedup table, and
  any app-controller state through ``checkpoint.CheckpointManager`` —
  one atomic manifest commit every ``MXNET_TPU_PS_CKPT_INTERVAL``
  applied mutations (on the handler thread, BEFORE the ack, so with
  interval 1 no acknowledged mutation can be lost) and on demand via
  the reserved ``ckpt`` command head.  A restarted server auto-restores
  from its newest valid manifest in ``__init__``.
- **Exactly-once retried mutations.**  Every mutating request
  (``push``/``init``/``set_optimizer``/``command``) carries a
  ``{"cid", "seq"}`` header; each shard keeps a
  bounded per-client last-applied-seq table (persisted with the store)
  and acks duplicates with the cached reply WITHOUT re-applying — a
  request whose reply is lost after the server applied it is therefore
  safe to retry, which is what makes ``command`` retryable and deletes
  the historical double-apply caveat.  ``barrier``/``stop`` stay
  never-retried (a double barrier arrival would desynchronize every
  later generation).
- **Liveness supervision.**  ``MXNET_TPU_KV_DEADLINE=<s>`` arms a
  worker-side heartbeat thread (guard-first: no thread, no probe
  sockets when unset) that pings idle shards on short-lived
  connections and warns (rate-limited, ``kvstore_dead_shard_warnings``
  counter) when a shard has had no successful contact past the
  deadline; under ``tools/launch.py`` with ``MXNET_TPU_SUPERVISE=N``
  a dead server process is relaunched (bounded restarts) and
  self-restores from its durable shard checkpoint.

Distributed telemetry (PR 7): each server shard keeps always-on
metrics — per-key bytes in/out and request counts, per-peer request
counts, optimizer-apply and message-handle latency histograms
(``histogram.py``), in-flight request depth, accepted connections (the
server-visible proxy for client reconnects/retries) — served to any
worker through a new ``stats`` head on the existing ``_command``
channel, so operators pull them with ``kv.server_stats()`` instead of
needing a side channel.  A ``ping`` head returns the server's wall
clock for the client's trace clock-offset estimate, and
``diag_put``/``diag_get`` let every rank park its diag dump on shard 0
for one-stop cluster aggregation (``tools/diagnose.py --cluster``).
Client-side, ``PSClient._call`` records per-shard push/pull RTT
histograms when collection is on and fires a rate-limited straggler
warning when one shard's RTT p99 diverges past
``MXNET_TPU_STRAGGLER_RATIO`` × the median shard p99.
"""

from __future__ import annotations

import io
import itertools
import json as _json
import os
import pickle
import socket
import struct
import threading
import time
import uuid

from .. import histogram as _histogram

__all__ = ["PSServer", "PSClient", "server_addresses", "run_server",
           "set_app_controller", "parse_fault_spec"]

_logger_cache: list = []


def _logger():
    if not _logger_cache:
        from ..log import get_logger

        _logger_cache.append(get_logger("mxnet_tpu.kvstore.ps"))
    return _logger_cache[0]


# --------------------------------------------------------- fault harness --
# Deterministic fault injection for the dist kvstore (MXNET_TPU_FAULT):
# the failure modes a real cluster produces nondeterministically —
# dropped/delayed/refused connections, lost replies, a parameter server
# dying mid-push — become reproducible test fixtures.  Injection is
# entirely server-side and counted under one lock, so "the Nth message"
# means the same message every run.  Crash-style faults fire BEFORE a
# message is handled (the in-flight mutation is neither applied nor
# acked, so its retry applies exactly once), while reply_drop fires
# AFTER handling — the apply succeeded but the ack is lost, which is
# precisely the window the (cid, seq) dedup table exists for.
#
#   MXNET_TPU_FAULT=drop_after:N     close the worker connection instead
#                                    of handling every Nth message
#   MXNET_TPU_FAULT=delay:S          sleep S seconds before each message
#   MXNET_TPU_FAULT=refuse:N         close the first N accepted
#                                    connections immediately
#   MXNET_TPU_FAULT=kill_after:N     stop the whole server upon receiving
#                                    the Nth message (before handling it)
#   MXNET_TPU_FAULT=reply_drop:N     handle every Nth message normally,
#                                    then close the connection instead of
#                                    sending the reply (exercises the
#                                    exactly-once dedup path)
#   MXNET_TPU_FAULT=restart_after:N  exit the server PROCESS nonzero
#                                    upon receiving the Nth message
#                                    (before handling it) so a
#                                    supervisor (MXNET_TPU_SUPERVISE)
#                                    revives it and it self-restores

_FAULT_MODES = ("drop_after", "delay", "refuse", "kill_after",
                "reply_drop", "restart_after")

# exit code of a restart_after drill: distinctive so the launcher's
# supervisor log lines are attributable to the injected fault
RESTART_FAULT_EXIT = 40


def parse_fault_spec(spec):
    """``MXNET_TPU_FAULT`` spec → ``{"mode", "arg"}`` or None."""
    if not spec:
        return None
    mode, _, arg = spec.partition(":")
    mode = mode.strip()
    if mode not in _FAULT_MODES:
        raise ValueError(
            "unknown MXNET_TPU_FAULT mode %r (known: %s)"
            % (mode, ", ".join(_FAULT_MODES)))
    return {"mode": mode,
            "arg": float(arg) if mode == "delay" else int(arg)}

# App-level server controller (reference: KVStore::RunServer(controller)):
# receives (head, body) for every non-framework command a worker sends via
# _send_command_to_servers; its return value travels back to the sender.
# mxlint: disable=thread-shared-state -- startup publication: registered once before the server accepts commands; handlers only read
_app_controller = [None]


def set_app_controller(fn):
    """Register fn(head, body) to handle app-level server commands;
    pass None to clear.

    The heads ``profiler``, ``stats``, ``ping``, ``diag_put``,
    ``diag_get`` and ``ckpt`` are RESERVED by the framework (telemetry
    + durability channel, docs/OBSERVABILITY.md "Distributed
    telemetry", docs/CHECKPOINTING.md "Server-side durability") and are
    intercepted before the app controller — pick other names.

    A controller that owns server-side state can expose
    ``fn.get_state() -> picklable`` / ``fn.set_state(state)``:
    durable shards (``MXNET_TPU_PS_CKPT``) persist that state with the
    store and hand it back on restore, so an app controller survives a
    server restart too.  Registration order does not matter — state
    restored before the controller existed is held by the server and
    delivered on the controller's first command."""
    _app_controller[0] = fn


# command heads the framework intercepts before the app controller
_RESERVED_HEADS = ("profiler", "stats", "ping", "diag_put", "diag_get",
                   "ckpt", "restart_rank", "restart_poll")


# modules/names a data message may reference: enough to rebuild numpy
# arrays, scalars, and dtypes — nothing that executes user code
_SAFE_PICKLE_GLOBALS = {
    ("numpy", ("ndarray", "dtype")),
    ("numpy.core.multiarray", ("_reconstruct", "scalar")),
    ("numpy._core.multiarray", ("_reconstruct", "scalar")),
    ("numpy.core.numeric", ("_frombuffer",)),
    ("numpy._core.numeric", ("_frombuffer",)),
}


class _DataUnpickler(pickle.Unpickler):
    """Unpickler for wire messages: numpy + builtins containers only."""

    def find_class(self, module, name):
        for mod, names in _SAFE_PICKLE_GLOBALS:
            if module == mod and name in names:
                return super().find_class(module, name)
        if module == "numpy.dtypes":  # numpy>=1.25 dtype classes
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            "wire message references forbidden global %s.%s" % (module, name))


class _OptimizerUnpickler(_DataUnpickler):
    """Unpickler for the set_optimizer blob: extends the data-message
    allowlist with optimizer and lr-scheduler CLASSES from this
    framework (the worker legitimately ships its configured Optimizer
    instance).  Every framework-module global must (a) be a plain name
    — dotted names would let proto-4 getattr traversal reach an allowed
    module's imports (e.g. ``pickle.loads``), which is exactly the
    bypass this class exists to prevent — and (b) resolve to an
    Optimizer or LRScheduler subclass.  Operators running custom
    optimizers over dist_async list the defining modules in
    MXTPU_PS_OPTIMIZER_MODULES (comma-separated; same class checks
    apply) — the reference has the same trust shape, where the server
    process must import the user's optimizer module to unpickle it."""

    _PREFIXES = ("mxnet_tpu.optimizer", "mxnet_tpu.lr_scheduler")

    def find_class(self, module, name):
        extra = tuple(m.strip() for m in os.environ.get(
            "MXTPU_PS_OPTIMIZER_MODULES", "").split(",") if m.strip())
        allowed = any(module == p or module.startswith(p + ".")
                      for p in self._PREFIXES + extra)
        if allowed and "." not in name:
            obj = super(_DataUnpickler, self).find_class(module, name)
            from ..lr_scheduler import LRScheduler
            from ..optimizer import Optimizer

            if isinstance(obj, type) and issubclass(
                    obj, (Optimizer, LRScheduler)):
                return obj
            raise pickle.UnpicklingError(
                "optimizer blob global %s.%s is not an Optimizer/"
                "LRScheduler class" % (module, name))
        return super().find_class(module, name)


def key_to_int(key):
    """Stable int for a kv key (updater index + shard assignment); int
    keys pass through like ps-lite's EncodeDefaultKey, string keys (the
    Gluon/Module path) hash via crc32."""
    if isinstance(key, int):
        return key
    try:
        return int(key)
    except (TypeError, ValueError):
        import zlib

        return zlib.crc32(str(key).encode())


# ------------------------------------------------------------- transport --
def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack(">Q", len(payload)) + payload)


def _recv_msg(sock):
    hdr = _recv_exact(sock, 8)
    if hdr is None:
        return None
    (n,) = struct.unpack(">Q", hdr)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    return _DataUnpickler(io.BytesIO(payload)).load()


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def server_addresses():
    """(host, [ports]) for the PS fleet from the DMLC_*/MXTPU_* env."""
    host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    ports = os.environ.get("MXTPU_PS_PORTS", "")
    if ports:
        return host, [int(p) for p in ports.split(",") if p]
    return host, [int(os.environ.get("DMLC_PS_ROOT_PORT", "9092"))]


# ---------------------------------------------------------------- server --
class PSServer:
    """One shard of the parameter server.

    Handlers mirror kvstore_dist_server.h: init stores, push applies the
    updater immediately (async semantics), pull returns current weights,
    set_optimizer installs the worker-pickled optimizer, barrier counts
    num_workers arrivals.
    """

    def __init__(self, port=0, host="127.0.0.1", num_workers=None):
        self._store = {}
        self._locks = {}
        self._store_lock = threading.Lock()
        # the updater (and its Optimizer) carries cross-key state
        # (num_update, schedulers) — per-key locks are not enough
        self._opt_lock = threading.Lock()
        self._updater = None
        self._num_workers = num_workers if num_workers is not None else \
            int(os.environ.get("DMLC_NUM_WORKER", 1))
        self._barrier_cv = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._stop = threading.Event()
        self._sock = socket.create_server((host, port))
        self.port = self._sock.getsockname()[1]
        self._conns = set()
        self._conns_lock = threading.Lock()
        # fault-injection state (parsed per server so tests can flip the
        # env between instances); message/refusal counters share one lock
        self._fault = parse_fault_spec(os.environ.get("MXNET_TPU_FAULT"))
        self._fault_lock = threading.Lock()
        self._fault_msgs = 0
        self._fault_refused = 0
        # server-side telemetry (always on: every request already pays a
        # network RTT, so the accounting is noise).  One lock covers the
        # cross-thread aggregates; the two latency histograms are
        # lock-free per the histogram module's contract.
        self._t_start = time.time()
        self._metrics_lock = threading.Lock()
        self._per_key = {}
        self._per_peer = {}
        self._op_counts = {}
        self._apply_hist = _histogram.Histogram()
        self._handle_hist = _histogram.Histogram()
        self._inflight = 0
        self._inflight_peak = 0
        self._accepted = 0
        # rank → diag-dump JSON string parked by the diag_put command
        self._rank_dumps = {}
        # worker-relaunch requests parked by the restart_rank command
        # (the autopilot's straggler reflex) until the launch.py
        # supervisor drains them via restart_poll; under _metrics_lock
        self._restart_requests = []
        self._server_id = int(os.environ.get(
            "MXTPU_PS_SERVER_ID",
            os.environ.get("DMLC_SERVER_ID", "0")) or 0)
        # per-key applied-mutation versions (init counts as version 1);
        # unlike _per_key's wire accounting these move only when a
        # mutation actually APPLIES, so dedup drills can assert
        # exactly-once server-side
        self._versions = {}
        # exactly-once dedup: cid → {"seq", "reply", "t"} of the last
        # APPLIED stamped request per client (bounded, LRU-evicted;
        # persisted with the store so it survives a restart)
        self._seq_lock = threading.Lock()
        self._seq = {}
        self._dup_suppressed = 0
        # pairs an apply with its seq-table record atomically AGAINST
        # durable-snapshot capture: a checkpoint must never see a seq
        # entry without its apply (a retry would be suppressed and the
        # mutation lost) nor an apply without its seq entry (a retry
        # would double-apply).  Mutations already serialize through
        # _opt_lock inside _apply, so this costs no real parallelism.
        self._mutate_lock = threading.Lock()
        # durable-shard state (MXNET_TPU_PS_CKPT): one CheckpointManager
        # per shard, SYNCHRONOUS writes on the handler thread so a
        # periodic commit always lands BEFORE the ack it covers
        self._opt_blob = None
        self._ckpt_lock = threading.Lock()
        self._ckpt_mgr = None
        self._ckpt_interval = 0
        self._mutations = 0
        self._last_ckpt_time = None
        self._restored_step = None
        # restored app-controller state awaiting a controller (one may
        # be registered after construction); applied lazily on its
        # first command and re-persisted until then
        self._app_state = None
        ckpt_dir = os.environ.get("MXNET_TPU_PS_CKPT")
        if ckpt_dir:
            from ..checkpoint import CheckpointManager

            self._ckpt_interval = int(os.environ.get(
                "MXNET_TPU_PS_CKPT_INTERVAL", "100") or 0)
            self._ckpt_mgr = CheckpointManager(
                os.path.join(ckpt_dir, "server%d" % self._server_id),
                async_write=False, prefix="ps")
            self._restore()

    # -- handler plumbing --------------------------------------------------
    def serve_forever(self):
        """Accept loop; one thread per worker connection.  Returns when a
        stop command arrives; open worker connections are closed so
        shutdown is observable client-side (a worker's next protocol
        read raises instead of blocking on a half-dead server)."""
        self._sock.settimeout(0.5)
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            if self._fault is not None and self._fault["mode"] == "refuse":
                with self._fault_lock:
                    refuse = self._fault_refused < self._fault["arg"]
                    if refuse:
                        self._fault_refused += 1
                if refuse:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
            with self._metrics_lock:
                # steady state is one connection per worker: growth past
                # that is the server-visible trace of client
                # reconnects/retries (PSClient._reconnect)
                self._accepted += 1
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        with self._conns_lock:
            for conn in list(self._conns):
                try:
                    conn.close()
                except OSError:
                    pass
        for t in threads:
            t.join(timeout=5)
        self._sock.close()
        if self._ckpt_mgr is not None:
            # a clean stop leaves the newest state durable even when
            # the interval boundary was not reached
            try:
                self._ckpt_save()
            except Exception:
                _logger().exception(
                    "final durable-shard checkpoint failed on stop")

    def _serve_conn(self, conn):
        try:
            peer = "%s:%d" % conn.getpeername()[:2]
        except OSError:
            peer = "<unknown>"
        try:
            while True:
                try:
                    msg = _recv_msg(conn)
                except Exception as e:
                    # a peer that cannot speak the framed-pickle
                    # protocol (or trips the restricted unpickler) is
                    # dropped; decode failures must never execute
                    # anything or kill the server thread — but they ARE
                    # logged (rate-limited per peer) with the address,
                    # so a flaky or hostile client is diagnosable
                    self._log_conn_error(peer, "undecodable frame", e)
                    return
                if msg is None:
                    return
                drop_reply = False
                # liveness 'ping' commands are FAULT-EXEMPT: the
                # heartbeat (MXNET_TPU_KV_DEADLINE) probes on its own
                # wall-clock cadence, and letting those messages
                # advance the fault counter would break the "the Nth
                # message is the same message every run" determinism
                # the drills are built on
                is_ping = msg[0] == "command" and len(msg) > 1 \
                    and msg[1] == "ping"
                if self._fault is not None and not is_ping:
                    action = self._fault_tick()
                    if action == "drop":
                        return
                    if action == "kill":
                        self._stop.set()
                        try:
                            self._sock.close()  # accept loop exits now
                        except OSError:
                            pass
                        return
                    if action == "restart":
                        # crash drill: die BEFORE handling (the in-flight
                        # mutation is neither applied nor acked) with a
                        # nonzero code so the launcher's supervisor
                        # revives the process; durable-shard writes are
                        # synchronous, so there is nothing to flush
                        _logger().warning(
                            "MXNET_TPU_FAULT=restart_after: server "
                            "shard %d exiting %d on message %d",
                            self._server_id, RESTART_FAULT_EXIT,
                            self._fault["arg"])
                        os._exit(RESTART_FAULT_EXIT)
                    drop_reply = action == "reply_drop"
                t_handle = time.perf_counter()
                with self._metrics_lock:
                    self._op_counts[msg[0]] = \
                        self._op_counts.get(msg[0], 0) + 1
                    self._per_peer[peer] = self._per_peer.get(peer, 0) + 1
                    self._inflight += 1
                    if self._inflight > self._inflight_peak:
                        self._inflight_peak = self._inflight
                try:
                    reply = self._handle(msg)
                except Exception as e:  # error surfaces on the worker
                    reply = ("err", "%s: %s" % (type(e).__name__, e))
                finally:
                    with self._metrics_lock:
                        self._inflight -= 1
                    self._handle_hist.observe(
                        time.perf_counter() - t_handle)
                if drop_reply:
                    # the request WAS handled (and, for a mutation,
                    # applied + recorded in the seq table); losing the
                    # reply forces the client through retry → dedup
                    return
                try:
                    _send_msg(conn, reply)
                except OSError as e:
                    # shutdown race: serve_forever closed this conn
                    # while the reply was in flight — drop, but logged
                    self._log_conn_error(peer, "reply send failed", e)
                    return
                if msg[0] == "stop":
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    def _log_conn_error(self, peer, what, exc):
        from .. import runtime_stats as _rts
        from ..log import warn_rate_limited

        _rts.inc("kvstore_server_conn_errors")
        warn_rate_limited(
            _logger(), "ps-conn:%s" % peer, 30,
            "dropping parameter-server connection from %s: %s (%s: %s)",
            peer, what, type(exc).__name__, exc)

    def _fault_tick(self):
        """Advance the injected-fault clock for one received message;
        returns 'drop', 'kill', 'restart', 'reply_drop', or None (after
        any injected delay)."""
        mode, arg = self._fault["mode"], self._fault["arg"]
        if mode == "delay":
            time.sleep(arg)
            return None
        if mode == "refuse":
            return None
        with self._fault_lock:
            self._fault_msgs += 1
            n = self._fault_msgs
        if mode == "drop_after" and arg > 0 and n % arg == 0:
            return "drop"
        if mode == "reply_drop" and arg > 0 and n % arg == 0:
            return "reply_drop"
        if mode == "kill_after" and n >= arg:
            return "kill"
        if mode == "restart_after" and n >= arg:
            return "restart"
        return None

    def _key_lock(self, key):
        with self._store_lock:
            if key not in self._locks:
                self._locks[key] = threading.Lock()
            return self._locks[key]

    # -- durable shard (MXNET_TPU_PS_CKPT) ---------------------------------
    # The store's numpy buffers are never mutated in place: init binds a
    # fresh copy and _apply REBINDS (`self._store[key] = weight.asnumpy()`),
    # so capturing references under _store_lock is a consistent snapshot
    # even while other keys keep applying — the same immutability argument
    # the worker-side zero-copy checkpoint rests on (checkpoint.py).

    def _restore(self):
        """Auto-restore this shard from its newest valid manifest:
        store + per-key versions, the dedup seq table, the optimizer
        blob (updater rebuilt through the allowlisted unpickler), and
        app-controller state.  A shard revived by the launcher's
        supervisor recovers its own state from disk — no operator or
        test-side seeding.

        Runs today only from __init__ (before serve threads exist),
        but rebinds the same state the handler threads read, so it
        takes the checkpoint locks in _ckpt_save's order
        (ckpt → mutate): a future live-restore command stays
        deadlock-free and snapshot-atomic by construction."""
        manifest = self._ckpt_mgr.latest()
        if manifest is None:
            return
        import numpy as np

        from .. import runtime_stats as _rts
        from ..checkpoint import load_aux

        aux = load_aux(manifest) or {}
        keys = list(aux.get("keys") or [])
        with self._ckpt_lock:
            with self._mutate_lock:
                with np.load(os.path.join(manifest["path"],
                                          "params.npz"),
                             allow_pickle=False) as data:
                    with self._store_lock:
                        self._store = {k: data["a%d" % i]
                                       for i, k in enumerate(keys)}
                with self._metrics_lock:
                    self._versions = dict(aux.get("versions") or {})
                with self._seq_lock:
                    self._seq = {cid: dict(ent) for cid, ent in
                                 (aux.get("seq_table") or {}).items()}
                blob = aux.get("optimizer_blob")
                if blob:
                    self._set_optimizer_locked(blob)
                app_state = aux.get("app_state")
                ctrl = _app_controller[0]
                if app_state is not None and hasattr(ctrl, "set_state"):
                    ctrl.set_state(app_state)
                elif app_state is not None:
                    # no controller registered (yet): carry the state
                    # so a controller installed after construction
                    # still receives it (applied lazily on its first
                    # command) and so it is re-persisted rather than
                    # silently dropped
                    self._app_state = app_state
            self._mutations = int(manifest.get("step", 0))
            self._restored_step = self._mutations
            n_keys, mutations = len(keys), self._mutations
        _rts.inc("kvstore_server_restores")
        _logger().info(
            "parameter-server shard %d restored %d key(s) at mutation "
            "%d from %s", self._server_id, n_keys, mutations,
            manifest["path"])

    def _ckpt_save(self):
        """Commit one durable snapshot of this shard (store + versions +
        seq table + optimizer blob + app-controller state) through the
        CheckpointManager; returns the manager's ``last_good`` record or
        None when durability is off."""
        if self._ckpt_mgr is None:
            return None
        with self._ckpt_lock:
            # capture under _mutate_lock: the snapshot must be
            # mutation-ATOMIC — store, seq table, and versions from the
            # same instant, with no apply/record pair straddling it
            with self._mutate_lock:
                with self._store_lock:
                    keys = list(self._store)
                    params = {"a%d" % i: self._store[k]
                              for i, k in enumerate(keys)}
                with self._seq_lock:
                    seq = {cid: dict(ent)
                           for cid, ent in self._seq.items()}
                with self._metrics_lock:
                    versions = dict(self._versions)
                aux = {"keys": keys, "versions": versions,
                       "seq_table": seq,
                       "optimizer_blob": self._opt_blob,
                       "mutations": self._mutations}
                ctrl = _app_controller[0]
                if hasattr(ctrl, "get_state"):
                    aux["app_state"] = ctrl.get_state()
                elif self._app_state is not None:
                    # restored state still awaiting its controller:
                    # keep persisting it, never silently drop it
                    aux["app_state"] = self._app_state
            self._ckpt_mgr.save(self._mutations, params, aux=aux)
            self._last_ckpt_time = time.time()
            return self._ckpt_mgr.last_good

    def _mutation_tick(self):
        """Advance the applied-mutation clock; at interval boundaries
        commit the durable snapshot BEFORE the handler's ack goes out
        (with MXNET_TPU_PS_CKPT_INTERVAL=1 every acknowledged mutation
        is therefore on disk — the bit-exact recovery drills rely on
        it; larger intervals trade a bounded window of acked-but-
        unpersisted mutations for fewer fsyncs)."""
        with self._ckpt_lock:
            self._mutations += 1
            due = self._ckpt_mgr is not None and self._ckpt_interval \
                and self._mutations % self._ckpt_interval == 0
        if due:
            self._ckpt_save()

    # -- exactly-once dedup ------------------------------------------------
    _SEQ_CLIENTS_MAX = 1024

    def _seq_check(self, meta):
        """Duplicate lookup for a stamped request: the cached reply when
        this ``(cid, seq)`` was already applied (the request is a retry
        whose original reply was lost), else None.  Never re-applies."""
        if not meta:
            return None
        with self._seq_lock:
            ent = self._seq.get(meta["cid"])
            if ent is None or meta["seq"] > ent["seq"]:
                return None
            reply = tuple(ent["reply"]) if meta["seq"] == ent["seq"] \
                else ("ok", None)
            self._dup_suppressed += 1
        from .. import runtime_stats as _rts

        _rts.inc("kvstore_dup_suppressed")
        return reply

    def _seq_record(self, meta, reply):
        """Record a stamped request's successful reply so a retry acks
        without re-applying.  One entry per client (the client protocol
        has one request in flight), LRU-bounded to ``_SEQ_CLIENTS_MAX``
        clients."""
        if not meta:
            return
        with self._seq_lock:
            self._seq[meta["cid"]] = {"seq": int(meta["seq"]),
                                      "reply": reply, "t": time.time()}
            while len(self._seq) > self._SEQ_CLIENTS_MAX:
                oldest = min(self._seq, key=lambda c: self._seq[c]["t"])
                del self._seq[oldest]

    def _note_apply(self, key):
        """Bump a key's applied-mutation version (init/push that really
        applied — duplicates never reach this)."""
        with self._metrics_lock:
            self._versions[key] = self._versions.get(key, 0) + 1

    # -- handlers ----------------------------------------------------------
    def _note_key(self, key, op, nbytes):
        """Per-key request/byte accounting (``stats`` command)."""
        with self._metrics_lock:
            d = self._per_key.get(key)
            if d is None:
                d = self._per_key[key] = {"init": 0, "push": 0, "pull": 0,
                                          "bytes_in": 0, "bytes_out": 0}
            d[op] += 1
            d["bytes_out" if op == "pull" else "bytes_in"] += int(nbytes)

    def _handle(self, msg):
        op = msg[0]
        # push/command carry an optional 4th element: the client's
        # {"cid", "seq"} exactly-once header (unstamped legacy messages
        # still handled)
        if op == "init":
            key, arr = msg[1], msg[2]
            meta = msg[3] if len(msg) > 3 else None
            self._note_key(key, "init", getattr(arr, "nbytes", 0))
            # init is stamped too: a reply-lost retried init would
            # otherwise re-bind the key and silently discard another
            # worker's push applied in the retry window
            dup = self._seq_check(meta)
            if dup is not None:
                return dup
            reply = ("ok", None)
            with self._mutate_lock:
                with self._key_lock(key):
                    self._store[key] = arr.copy()
                self._note_apply(key)
                self._seq_record(meta, reply)
            self._mutation_tick()
            return reply
        if op == "push":
            key, grad = msg[1], msg[2]
            meta = msg[3] if len(msg) > 3 else None
            from .. import profiler

            self._note_key(key, "push", getattr(grad, "nbytes", 0))
            dup = self._seq_check(meta)
            if dup is not None:
                return dup
            reply = ("ok", None)
            with profiler.scope("ps_push:%s" % (key,), "kvstore"):
                # apply + seq record as one unit w.r.t. snapshot
                # capture (see _mutate_lock), BEFORE the durable
                # commit: a crash before the commit leaves the
                # mutation unacked and unpersisted, so the retry
                # re-applies exactly once on the restored store
                with self._mutate_lock:
                    with self._key_lock(key):
                        if key not in self._store:
                            raise KeyError(
                                "key %r not initialized" % (key,))
                        t0 = time.perf_counter()
                        self._apply(key, grad)
                        self._apply_hist.observe(
                            time.perf_counter() - t0)
                    self._note_apply(key)
                    self._seq_record(meta, reply)
            self._mutation_tick()
            return reply
        if op == "pull":
            key = msg[1]
            from .. import profiler

            with profiler.scope("ps_pull:%s" % (key,), "kvstore"):
                with self._key_lock(key):
                    if key not in self._store:
                        raise KeyError("key %r not initialized" % (key,))
                    out = self._store[key].copy()
            self._note_key(key, "pull", getattr(out, "nbytes", 0))
            return ("ok", out)
        if op == "set_optimizer":
            blob = msg[1]
            meta = msg[2] if len(msg) > 2 else None
            dup = self._seq_check(meta)
            if dup is not None:
                return dup
            reply = ("ok", None)
            with self._mutate_lock:
                self._set_optimizer_locked(blob)
                self._seq_record(meta, reply)
            # the optimizer blob is part of the durable state: count it
            # toward the snapshot cadence so an acked set_optimizer at
            # interval 1 survives a crash (a revived server must not
            # train with stale hyperparameters — or no updater at all)
            self._mutation_tick()
            return reply
        if op == "command":
            head, body = msg[1], msg[2]
            meta = msg[3] if len(msg) > 3 else None
            dup = self._seq_check(meta)
            if dup is not None:
                return dup
            if head in _RESERVED_HEADS or _app_controller[0] is None:
                # framework heads are read-only (or, for 'ckpt', take
                # the checkpoint locks themselves) — no mutation pairing
                reply = ("ok", self._command(head, body))
                self._seq_record(meta, reply)
                return reply
            # an app-controller command may mutate the state the
            # controller owns: run + seq-record as one unit w.r.t.
            # snapshot capture, and count it toward the durable cadence
            with self._mutate_lock:
                ctrl = _app_controller[0]
                if self._app_state is not None and \
                        hasattr(ctrl, "set_state"):
                    # controller registered after construction: hand it
                    # the restored state before its first command
                    ctrl.set_state(self._app_state)
                    self._app_state = None
                # dispatch straight to the controller: reserved heads
                # never reach this branch, and routing back through
                # _command while holding _mutate_lock would self-
                # deadlock on the non-reentrant lock if a framework
                # head ('ckpt' takes _mutate_lock itself) ever slipped
                # through
                reply = ("ok", ctrl(head, body))
                self._seq_record(meta, reply)
            self._mutation_tick()
            return reply
        if op == "barrier":
            self._barrier()
            return ("ok", None)
        if op == "stop":
            self._stop.set()
            return ("ok", None)
        raise ValueError("unknown op %r" % (op,))

    def _apply(self, key, grad):
        """Async update: every push applies immediately (reference:
        kvstore_dist_server.h DataHandleDefault async branch)."""
        if self._updater is None:
            # reference: "Updater needs to be set for async mode"
            # (kvstore_dist_server.h:358 CHECK(sync_mode_))
            raise RuntimeError(
                "set_optimizer must be called before push on dist_async")
        from .. import ndarray as nd

        weight = nd.array(self._store[key])
        with self._opt_lock:
            self._updater(key_to_int(key), nd.array(grad), weight)
        self._store[key] = weight.asnumpy()

    def _set_optimizer_locked(self, blob):
        from .. import optimizer as opt_mod

        # the worker ships its Optimizer instance like the reference's
        # kv.set_optimizer pickled blob, but decoding is allowlisted to
        # this framework's optimizer/scheduler classes (r3; closes the
        # r2 residual wire caveat).  The raw blob is kept so durable
        # shards can persist it and a revived server rebuilds its
        # updater without the worker re-shipping it.
        optimizer = _OptimizerUnpickler(io.BytesIO(blob)).load()
        self._updater = opt_mod.get_updater(optimizer)
        self._opt_blob = blob

    def stats_snapshot(self):
        """This shard's server-side metrics as one JSON-ready dict —
        the payload of the ``stats`` command.  ``connections_accepted``
        above one per worker is the server-visible trace of client
        reconnects/retries; ``queue_depth`` is the in-flight request
        gauge at snapshot time (its ``_peak`` the high-water mark).
        ``per_key[...]["version"]`` counts APPLIED mutations (dedup'd
        retries excluded); ``dedup`` and ``durability`` describe the
        exactly-once table and the shard's durable-checkpoint state
        (docs/CHECKPOINTING.md "Server-side durability")."""
        from .. import runtime_stats as _rts

        with self._metrics_lock:
            versions = dict(self._versions)
            per_key = {str(k): dict(v, version=versions.get(k, 0))
                       for k, v in self._per_key.items()}
            per_peer = dict(self._per_peer)
            requests = dict(self._op_counts)
            inflight, peak = self._inflight, self._inflight_peak
            accepted = self._accepted
            rank_dumps = sorted(self._rank_dumps)
        with self._fault_lock:
            fault = None if self._fault is None else dict(
                self._fault, messages=self._fault_msgs,
                refused=self._fault_refused)
        with self._seq_lock:
            dedup = {"clients": len(self._seq),
                     "suppressed": self._dup_suppressed}
        mgr = self._ckpt_mgr
        # the durability fields are written under _ckpt_lock
        # (_mutation_tick / _ckpt_save / _restore): read them under the
        # same lock so the mutation clock and last-checkpoint stamp in
        # one snapshot belong to the same instant
        with self._ckpt_lock:
            durability = {"enabled": mgr is not None,
                          "mutations": self._mutations}
            if mgr is not None:
                lg = mgr.last_good
                durability.update({
                    "directory": mgr.directory,
                    "interval": self._ckpt_interval,
                    "saves": mgr.totals["written"],
                    "last_ckpt_step": lg["step"] if lg else None,
                    "last_ckpt_path": lg["path"] if lg else None,
                    "last_ckpt_time": self._last_ckpt_time,
                    "restored_step": self._restored_step})
        with self._store_lock:
            n_keys = len(self._store)
        return {"role": "server",
                "server_id": self._server_id,
                "pid": os.getpid(), "time": time.time(),
                "uptime_seconds": time.time() - self._t_start,
                "keys": n_keys,
                "requests": requests,
                "per_key": per_key,
                "per_peer": per_peer,
                "queue_depth": inflight,
                "queue_depth_peak": peak,
                "connections_accepted": accepted,
                "conn_errors": _rts._COUNTERS.get(
                    "kvstore_server_conn_errors", 0),
                "apply": self._apply_hist.snapshot(),
                "handle": self._handle_hist.snapshot(),
                "fault": fault,
                "dedup": dedup,
                "durability": durability,
                "rank_dumps": rank_dumps}

    def _command(self, head, body):
        """Controller channel (reference: ps-lite server commands;
        KVStoreServerProfilerCommand include/mxnet/kvstore.h:49).
        'profiler' drives this server process's profiler so pushes can be
        traced server-side (reference: tests/nightly/
        test_server_profiling.py).  'stats' returns this shard's
        server-side metrics, 'ping' its wall clock (the client's trace
        clock-offset probe), 'diag_put'/'diag_get' park / serve
        per-rank diag dumps for cluster aggregation
        (docs/OBSERVABILITY.md "Distributed telemetry"), and 'ckpt'
        commits the durable shard snapshot on demand
        (docs/CHECKPOINTING.md "Server-side durability").  Any other
        head goes to the app-level controller when one is registered
        (reference: KVStore::RunServer's controller argument)."""
        if head == "stats":
            return _json.dumps(self.stats_snapshot())
        if head == "ckpt":
            if self._ckpt_mgr is None:
                return _json.dumps({"enabled": False, "step": None,
                                    "path": None})
            lg = self._ckpt_save()
            return _json.dumps({"enabled": True,
                                "step": lg["step"] if lg else None,
                                "path": lg["path"] if lg else None})
        if head == "ping":
            return _json.dumps({"t_server": time.time(),
                                "pid": os.getpid()})
        if head == "diag_put":
            # body = "<rank key>\n<json dump>": the key travels outside
            # the payload so this handler thread never JSON-parses a
            # potentially large dump; a bare-JSON body (no key line)
            # falls back to reading the identity from the payload
            key, sep, payload = (body or "").partition("\n")
            if not sep or key.lstrip().startswith("{"):
                payload = body or ""
                ident = (_json.loads(payload).get("identity") or {}) \
                    if payload else {}
                key = "%s %s" % (ident.get("role", "worker"),
                                 ident.get("rank", "?"))
            with self._metrics_lock:
                self._rank_dumps[key.strip()] = payload
            return None
        if head == "diag_get":
            with self._metrics_lock:
                return dict(self._rank_dumps)
        if head == "restart_rank":
            # body = JSON {"rank": int, "reason": str} (a bare int
            # body also parses): park the request for the supervisor.
            # The server only RECORDS — relaunch authority stays with
            # the process that owns the worker (tools/launch.py
            # --supervise), so an unsupervised run degrades to a
            # visible no-op instead of a kill.
            try:
                req = _json.loads(body or "{}")
            except ValueError:
                raise ValueError("restart_rank body must be JSON, got "
                                 "%r" % (body,))
            if isinstance(req, int):
                req = {"rank": req}
            if not isinstance(req, dict) or not isinstance(
                    req.get("rank"), int):
                raise ValueError("restart_rank body needs an integer "
                                 "'rank', got %r" % (body,))
            rec = {"rank": req["rank"],
                   "reason": str(req.get("reason", "")), "t": time.time()}
            with self._metrics_lock:
                self._restart_requests.append(rec)
                # bounded: a supervisor-less run must not grow forever
                del self._restart_requests[:-64]
            from .. import runtime_stats as _rts

            _rts.inc("kvstore_restart_requests")
            return _json.dumps({"parked": True, "rank": rec["rank"]})
        if head == "restart_poll":
            # drain-and-return: each request is handed to exactly one
            # poller (the supervisor loop)
            with self._metrics_lock:
                out = list(self._restart_requests)
                del self._restart_requests[:]
            return _json.dumps(out)
        if head != "profiler":
            if _app_controller[0] is not None:
                return _app_controller[0](head, body)
            raise ValueError("unknown server command %r" % (head,))
        from .. import profiler

        req = _json.loads(body)
        fn, kwargs = req["fn"], req.get("kwargs", {})
        if fn == "set_config":
            if "filename" in kwargs:
                # each server shard writes its own trace
                base, ext = os.path.splitext(kwargs["filename"])
                sid = os.environ.get("MXTPU_PS_SERVER_ID", "0")
                kwargs["filename"] = "%s.server%s%s" % (base, sid, ext)
            profiler.set_config(**kwargs)
        elif fn == "set_state":
            profiler.set_state(**kwargs)
        elif fn == "pause":
            profiler.pause(**kwargs)
        elif fn == "resume":
            profiler.resume(**kwargs)
        elif fn == "dump":
            return profiler.dump(**kwargs)
        else:
            raise ValueError("unknown profiler fn %r" % (fn,))
        return None

    def _barrier(self):
        with self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= self._num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cv.notify_all()
                return
            ok = self._barrier_cv.wait_for(
                lambda: self._barrier_gen != gen, timeout=300)
            if not ok:
                # withdraw our arrival so a late worker cannot release
                # the NEXT generation with this stale count, then fail
                # loudly (a silent release desynchronizes every
                # subsequent barrier)
                if self._barrier_gen == gen and self._barrier_count > 0:
                    self._barrier_count -= 1
                raise RuntimeError(
                    "barrier timed out after 300s waiting for %d workers"
                    % self._num_workers)


def run_server(port=None, num_workers=None):
    """Blocking server entry (reference: kvstore_server.py server loop)."""
    if port is None:
        _, ports = server_addresses()
        idx = int(os.environ.get("MXTPU_PS_SERVER_ID",
                                 os.environ.get("DMLC_SERVER_ID", 0)))
        port = ports[idx % len(ports)]
    server = PSServer(port=port, num_workers=num_workers)
    server.serve_forever()


# ---------------------------------------------------------------- client --
class PSClient:
    """Worker-side connections to every server shard; key → shard by
    int_key % num_servers (reference: EncodeDefaultKey).

    Transient transport errors (connection reset/refused/closed —
    ps-lite's van resend territory) are retried with bounded
    exponential backoff and a fresh dial of the failed shard
    (``MXNET_TPU_KV_RETRIES`` / ``MXNET_TPU_KV_RETRY_BACKOFF``), so a
    flaky network or a briefly-restarting server no longer kills the
    worker on the first socket error.  Exhausted retries raise a clear
    ``MXNetError`` naming the shard.  Retried mutations are
    **exactly-once**: every ``push``/``init``/``set_optimizer``/
    ``command`` is stamped with this client's ``(cid, seq)`` header
    and the server's per-client
    last-applied-seq table acks a retry whose original reply was lost
    with the cached reply, without re-applying — which is also what
    makes ``command`` (app-level controllers run arbitrary,
    non-idempotent code) safe to retry.  Only ``barrier``/``stop``
    are never retried: a double barrier arrival would desynchronize
    every subsequent generation, and dedup cannot help because a
    barrier's effect (blocking a generation) is not a replayable reply.

    Liveness supervision (``MXNET_TPU_KV_DEADLINE=<seconds>``): a
    heartbeat thread pings idle shards on short-lived probe
    connections and warns (rate-limited,
    ``kvstore_dead_shard_warnings`` counter) when a shard has had no
    successful contact past the deadline — the in-job detector for a
    dead server process before retries exhaust.  Guard-first: with the
    env unset (the default) there is no thread, no probe socket, and
    the per-request cost is the O(1) seq stamp
    (``tests/test_bench_gate.py`` pins it).
    """

    _NON_RETRYABLE_OPS = ("barrier", "stop")

    # RTT ops measured into per-shard latency histograms; every
    # _RTT_CHECK_EVERY observations the straggler detector compares
    # shard p99s (both only when histogram collection is on)
    _RTT_OPS = ("push", "pull")
    _RTT_CHECK_EVERY = 64

    def __init__(self, connect_timeout=60):
        host, ports = server_addresses()
        self._addrs = [(host, p) for p in ports]
        self._max_retries = int(os.environ.get(
            "MXNET_TPU_KV_RETRIES", "5"))
        self._backoff = float(os.environ.get(
            "MXNET_TPU_KV_RETRY_BACKOFF", "0.1"))
        self._socks = [self._dial(a, connect_timeout)
                       for a in self._addrs]
        self._lock = threading.Lock()
        self._rtt_obs = 0
        # exactly-once identity: one cid per client object, a monotonic
        # seq per stamped request (itertools.count: atomic under the
        # GIL, no lock on the stamp path)
        self._cid = uuid.uuid4().hex[:16]
        self._seq_counter = itertools.count(1)
        # liveness supervision (MXNET_TPU_KV_DEADLINE): guard-first —
        # no thread, no probe sockets, no last-seen bookkeeping unless
        # the deadline is set
        self._last_ok = [time.monotonic()] * len(self._addrs)
        self._deadline = float(os.environ.get(
            "MXNET_TPU_KV_DEADLINE", "0") or 0)
        self._hb_stop = None
        self._hb_thread = None
        if self._deadline > 0:
            self._hb_stop = threading.Event()
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                name="mxtpu-kv-heartbeat", daemon=True)
            self._hb_thread.start()

    @staticmethod
    def _dial(addr, connect_timeout, dial_timeout=300):
        # the launcher Popens servers and workers back-to-back; a
        # server binds its port only after its (slow) import, so
        # refused connections are a startup race, not an error —
        # retry until the deadline
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                s = socket.create_connection(addr, timeout=dial_timeout)
                break
            except ConnectionRefusedError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        # create_connection's timeout is only for the dial; a blocking
        # protocol op (barrier chains, large pulls, slow server-side
        # optimizer) may legitimately exceed it, and a mid-protocol
        # socket.timeout would desynchronize the framed stream
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _stamp(self):
        """The per-request exactly-once header: ``{"cid", "seq"}``.
        O(1) — one counter increment and one small dict
        (``tests/test_bench_gate.py`` pins the bound).

        The cid is per (client, thread): the server's dedup table keeps
        only the LAST seq per cid, which is correct iff each cid has at
        most one request in flight — true per thread by construction
        (a thread blocks in ``_call`` until its request resolves), but
        NOT across threads sharing one cid (thread B's later seq could
        land first and make thread A's retry look like a stale
        duplicate, silently dropping a real mutation)."""
        return {"cid": "%s-%x" % (self._cid, threading.get_ident()),
                "seq": next(self._seq_counter)}

    def _probe_shard(self, idx):
        """One liveness ping on a fresh short-timeout connection —
        never touches the request path's sockets or lock, so a wedged
        shard cannot stall healthy traffic.  True iff the shard
        answered."""
        timeout = max(min(2.0, self._deadline / 2.0), 0.1)
        try:
            s = socket.create_connection(self._addrs[idx],
                                         timeout=timeout)
        except OSError:
            return False
        try:
            s.settimeout(timeout)
            _send_msg(s, ("command", "ping", ""))
            return _recv_msg(s) is not None
        except Exception:
            return False
        finally:
            try:
                s.close()
            except OSError:
                pass

    def _heartbeat_loop(self):
        """Liveness supervision: every ``deadline/3`` seconds, probe
        shards with no recent successful traffic; a shard silent past
        ``MXNET_TPU_KV_DEADLINE`` gets a rate-limited warning naming
        the shard and its last-seen age, counted in
        ``kvstore_dead_shard_warnings``."""
        from .. import runtime_stats as _rts
        from ..log import warn_rate_limited

        interval = max(self._deadline / 3.0, 0.05)
        while not self._hb_stop.wait(interval):
            for idx, addr in enumerate(self._addrs):
                if time.monotonic() - self._last_ok[idx] < interval:
                    continue  # recent traffic already proves liveness
                if self._probe_shard(idx):
                    self._last_ok[idx] = time.monotonic()
                    continue
                age = time.monotonic() - self._last_ok[idx]
                if age < self._deadline:
                    continue
                if warn_rate_limited(
                        _logger(), "kv-dead:%d" % idx,
                        max(self._deadline, 5.0),
                        "parameter-server shard %d (%s:%d) is "
                        "unresponsive: no successful contact for %.1fs "
                        "(MXNET_TPU_KV_DEADLINE=%.1fs) — in-flight "
                        "requests retry with backoff and raise a clear "
                        "MXNetError when exhausted; under "
                        "tools/launch.py MXNET_TPU_SUPERVISE a dead "
                        "server process is relaunched and self-restores "
                        "(docs/CHECKPOINTING.md 'Server-side "
                        "durability')",
                        idx, addr[0], addr[1], age, self._deadline):
                    _rts.inc("kvstore_dead_shard_warnings")

    def _shard(self, key):
        """Shard INDEX for a key (indices stay valid across reconnects;
        socket objects do not)."""
        return key_to_int(key) % len(self._socks)

    def _reconnect(self, idx):
        """Redial one shard after a transport error; the (possibly
        slow) dial happens OUTSIDE the client lock so RPCs to healthy
        shards keep flowing, and the fresh socket is swapped in under
        it.  Returns True when a fresh connection is in place (a failed
        dial leaves the dead socket — the next attempt's send fails
        fast and retries again)."""
        from .. import runtime_stats as _rts

        with self._lock:
            try:
                self._socks[idx].close()
            except OSError:
                pass
        try:
            s = self._dial(self._addrs[idx], connect_timeout=0,
                           dial_timeout=5)
        except OSError:
            return False
        with self._lock:
            self._socks[idx] = s
        _rts.inc("kvstore_reconnects")
        return True

    def _call(self, target, msg):
        """One request/response round on a shard.  ``target`` is a
        shard index (the internal form) or a socket object (accepted
        for compatibility; resolved to its index when possible)."""
        from .. import runtime_stats as _rts
        from ..log import warn_rate_limited

        if isinstance(target, int):
            idx, sock = target, None
        else:
            sock = target
            with self._lock:
                try:
                    idx = self._socks.index(sock)
                except ValueError:
                    idx = None
        retryable = idx is not None and \
            msg[0] not in self._NON_RETRYABLE_OPS and \
            self._max_retries > 0
        # per-shard RTT distribution (guard-first; timestamps only while
        # collecting).  Each attempt is timed alone: a retried request's
        # failed rounds must not smear the successful round's latency.
        # t0 is taken INSIDE the client lock — waiting for another
        # thread's round trip is queueing, not shard RTT, and counting
        # it would fire straggler warnings at healthy shards.
        rtt_on = idx is not None and msg[0] in self._RTT_OPS and \
            _histogram._state["on"]
        attempt = 0
        while True:
            try:
                with self._lock:
                    if rtt_on:
                        t0 = time.perf_counter()
                    s = self._socks[idx] if idx is not None else sock
                    _send_msg(s, msg)
                    reply = _recv_msg(s)
                if reply is None:
                    raise ConnectionError(
                        "parameter server closed the connection")
                if self._hb_thread is not None and idx is not None:
                    self._last_ok[idx] = time.monotonic()
                if rtt_on:
                    dur = time.perf_counter() - t0
                    _histogram.observe("kv:%s_rtt" % msg[0], dur)
                    _histogram.observe(
                        "kv:%s_rtt:shard%d" % (msg[0], idx), dur)
                    self._maybe_warn_straggler()
                break
            except (ConnectionError, socket.timeout, OSError) as e:
                if not retryable:
                    raise
                if attempt >= self._max_retries:
                    from ..base import MXNetError

                    seen = ""
                    if self._hb_thread is not None:
                        seen = "; last successful contact %.1fs ago" \
                            % (time.monotonic() - self._last_ok[idx])
                    raise MXNetError(
                        "parameter server shard %d (%s:%d) unreachable "
                        "after %d retries with backoff (%s op, last "
                        "error %s: %s%s) — check the server process / "
                        "network, or raise MXNET_TPU_KV_RETRIES"
                        % (idx, self._addrs[idx][0], self._addrs[idx][1],
                           self._max_retries, msg[0],
                           type(e).__name__, e, seen)) from e
                delay = min(self._backoff * (2 ** attempt), 2.0)
                attempt += 1
                _rts.inc("kvstore_retries")
                warn_rate_limited(
                    _logger(), "ps-retry:%d" % idx, 10,
                    "transient parameter-server error on shard %d "
                    "(%s:%d): %s: %s — retry %d/%d in %.2fs",
                    idx, self._addrs[idx][0], self._addrs[idx][1],
                    type(e).__name__, e, attempt, self._max_retries,
                    delay)
                time.sleep(delay)
                self._reconnect(idx)
        status, payload = reply
        if status != "ok":
            from ..base import MXNetError

            raise MXNetError("parameter server error: %s" % payload)
        return payload

    def _maybe_warn_straggler(self):
        """Every ``_RTT_CHECK_EVERY`` RTT observations, compare the
        per-shard push-RTT p99s and warn (rate-limited, counted) when
        one shard has diverged past ``MXNET_TPU_STRAGGLER_RATIO`` × the
        median — the live, in-job form of the cluster report's
        straggler callout."""
        self._rtt_obs += 1
        if self._rtt_obs % self._RTT_CHECK_EVERY or len(self._socks) < 2:
            return
        found = _histogram.detect_straggler("kv:push_rtt:shard") \
            or _histogram.detect_straggler("kv:pull_rtt:shard")
        if found is None:
            return
        from .. import runtime_stats as _rts
        from ..log import warn_rate_limited

        if warn_rate_limited(
                _logger(), "kv-straggler",
                _histogram.STRAGGLER_WARN_INTERVAL,
                "parameter-server straggler: %s p99 %.1fms is %.1fx the "
                "median shard p99 (%.1fms) — that shard's host/network "
                "is holding the job back (docs/OBSERVABILITY.md "
                "'Distributed telemetry')",
                found["name"], found["p99"] * 1e3, found["ratio"],
                found["median_p99"] * 1e3):
            _rts.inc("kvstore_straggler_warnings")

    def init(self, key, arr):
        self._call(self._shard(key),
                   ("init", key, arr, self._stamp()))

    def push(self, key, grad):
        self._call(self._shard(key),
                   ("push", key, grad, self._stamp()))

    def pull(self, key):
        return self._call(self._shard(key), ("pull", key))

    def command_shard(self, idx, head, body=""):
        """App/controller command on ONE shard, returning its reply
        payload (``send_command`` broadcasts and discards replies —
        the telemetry heads need the answer).  Stamped with the
        exactly-once header, so a retried command is acked from the
        server's seq table instead of running twice."""
        return self._call(idx, ("command", head, body, self._stamp()))

    def server_stats(self):
        """Every shard's server-side metrics (the ``stats`` command),
        as a list of dicts indexed by shard."""
        return [_json.loads(self.command_shard(i, "stats"))
                for i in range(len(self._socks))]

    def checkpoint_shards(self):
        """Force every shard to commit its durable snapshot NOW (the
        reserved ``ckpt`` command head): one
        ``{"enabled", "step", "path"}`` dict per shard — ``enabled``
        False when that server runs without ``MXNET_TPU_PS_CKPT``
        (docs/CHECKPOINTING.md "Server-side durability")."""
        return [_json.loads(self.command_shard(i, "ckpt"))
                for i in range(len(self._socks))]

    def request_restart(self, rank, reason=""):
        """Park a worker-relaunch request on shard 0 (the reserved
        ``restart_rank`` head).  The ``tools/launch.py --supervise``
        loop polls ``restart_poll`` and relaunches that worker through
        the PR 9 supervise/auto-resume path; without a supervisor the
        request is a recorded no-op.  Returns the shard's ack dict."""
        body = _json.dumps({"rank": int(rank),
                            "reason": str(reason)})
        return _json.loads(
            self.command_shard(0, "restart_rank", body))

    def ping(self, idx=0, samples=5):
        """Estimate this process's wall-clock offset to shard ``idx``:
        returns ``(offset_seconds, rtt_seconds)`` from the
        lowest-RTT of ``samples`` pings (midpoint method — the offset
        error is bounded by rtt/2).  Feeds the merged-trace clock
        alignment (``profiler.set_clock_offset``)."""
        best = None
        for _ in range(samples):
            t0 = time.perf_counter()
            w0 = time.time()
            reply = _json.loads(self.command_shard(idx, "ping"))
            rtt = time.perf_counter() - t0
            w1 = time.time()
            offset = reply["t_server"] - (w0 + w1) / 2.0
            if best is None or rtt < best[1]:
                best = (offset, rtt)
        return best

    def set_optimizer(self, blob):
        for i in range(len(self._socks)):
            self._call(i, ("set_optimizer", blob, self._stamp()))

    def send_command(self, head, body):
        for i in range(len(self._socks)):
            self._call(i, ("command", head, body, self._stamp()))

    def barrier(self):
        # every server counts all workers; hitting each keeps shards in step
        for i in range(len(self._socks)):
            self._call(i, ("barrier",))

    def stop_servers(self):
        for i in range(len(self._socks)):
            try:
                self._call(i, ("stop",))
            except Exception:
                pass

    def close(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
