"""``mx.kv`` — KVStore (reference: include/mxnet/kvstore.h, src/kvstore/)."""

from .kvstore import KVStore, create  # noqa: F401
from .gradient_compression import GradientCompression  # noqa: F401
