"""Async dependency engine (host side).

Reference: include/mxnet/engine.h:115-314 Engine::{NewVariable,PushAsync,
WaitForVar,WaitForAll} and src/engine/threaded_engine.h.  On TPU the
device-side role of the reference engine — ordering CUDA kernels without
blocking the user thread — is owned by XLA's async runtime (every jax op
dispatches asynchronously already).  What still needs an engine is HOST
work: data-pipeline stages, checkpoint writes, metric host syncs, custom
Python ops.  This module exposes the reference Engine API backed by the
native C++ engine (mxnet_tpu/native/src/engine.cc) with a synchronous
pure-Python fallback (the NaiveEngine analog, src/engine/naive_engine.cc).

Select with MXNET_ENGINE_TYPE=ThreadedEngine|NaiveEngine (reference env
var; default ThreadedEngine when the native library is available).
"""

from __future__ import annotations

import ctypes
import os
import threading

from . import _native

# FnProperty (reference include/mxnet/engine.h:73)
NORMAL = 0
IO = 1
PRIORITY = 2
ASYNC = 3


class NaiveEngine:
    """Synchronous fallback: ops run inline at Push (reference
    src/engine/naive_engine.cc — also useful for debugging races)."""

    def __init__(self):
        self._versions = {}
        self._next = 1
        self._next_op = 1
        self._errors = {}
        self._async_vars = {}  # op_id -> mutable var list

    def new_variable(self):
        v = self._next
        self._next += 1
        self._versions[v] = 0
        return v

    def delete_variable(self, var):
        self._versions.pop(var, None)
        self._errors.pop(var, None)

    def push(self, fn, const_vars=(), mutable_vars=(), prop=NORMAL, name=""):
        op_id = self._next_op
        self._next_op += 1
        try:
            if prop == ASYNC:
                # same contract as ThreadedEngine: fn(op_id) initiates;
                # on_complete(_error) finishes.  Synchronous engine cannot
                # block on it — deps resolve eagerly (debug engine).
                self._async_vars[op_id] = list(mutable_vars)
                fn(op_id)
                return op_id
            fn()
        except Exception as e:  # record on written vars like the threaded engine
            for v in mutable_vars:
                self._errors[v] = e
            return op_id
        for v in mutable_vars:
            self._versions[v] = self._versions.get(v, 0) + 1
            self._errors.pop(v, None)  # a clean write clears a stale error
        return op_id

    def on_complete(self, op_id):
        for v in self._async_vars.pop(op_id, ()):
            self._versions[v] = self._versions.get(v, 0) + 1
            self._errors.pop(v, None)

    def on_complete_error(self, op_id, msg):
        err = RuntimeError(str(msg))
        for v in self._async_vars.pop(op_id, ()):
            self._errors[v] = err

    def wait_for_var(self, var):
        if var in self._errors:
            raise self._errors[var]

    def wait_all(self):
        pass

    @property
    def num_pending(self):
        return 0


class ThreadedEngine:
    """Native C++ threaded dependency engine via ctypes."""

    def __init__(self, n_workers=None, io_workers=None):
        lib = _native.get_lib()
        if lib is None:
            raise RuntimeError("native engine unavailable")
        self._lib = lib
        if n_workers is None:
            n_workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS",
                                           max(2, (os.cpu_count() or 4) // 2)))
        if io_workers is None:
            io_workers = int(os.environ.get("MXNET_CPU_IO_NTHREADS", 2))
        h = ctypes.c_void_p()
        _native.check_call(lib.MXTPUEngineCreate(n_workers, io_workers,
                                                 ctypes.byref(h)))
        self._h = h
        # ONE persistent ffi trampoline for the engine's lifetime; per-op
        # Python fns are looked up (and removed) by the integer key passed
        # through the C `ctx` pointer.  Freeing per-op CFUNCTYPE closures
        # from inside their own call would be a use-after-free.
        self._fns = {}
        self._next_key = 0
        self._cb_lock = threading.Lock()
        self._last_op_error = None
        self._trampoline = _native.ENGINE_OP_FN(self._dispatch)

    def _dispatch(self, ctx, op_id):
        with self._cb_lock:
            entry = self._fns.pop(ctx, None)
        if entry is None:
            return 1
        fn, is_async = entry
        try:
            # kAsync ops receive their op id and must later call
            # on_complete(op_id) / on_complete_error(op_id, msg).
            fn(op_id) if is_async else fn()
            return 0
        except Exception:
            import traceback
            with self._cb_lock:
                self._last_op_error = traceback.format_exc()
            return 1

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.MXTPUEngineFree(self._h)
                self._h = None
        except Exception:
            pass

    def new_variable(self):
        v = ctypes.c_uint64()
        _native.check_call(self._lib.MXTPUEngineNewVar(self._h,
                                                       ctypes.byref(v)))
        return v.value

    def delete_variable(self, var):
        _native.check_call(self._lib.MXTPUEngineDelVar(self._h, var))

    def push(self, fn, const_vars=(), mutable_vars=(), prop=NORMAL, name=""):
        """Schedule fn() once all deps resolve; returns op id.

        With prop=ASYNC, fn(op_id) only *initiates* the work; the var deps
        stay held until on_complete(op_id)/on_complete_error(op_id, msg)
        (reference: Engine::PushAsync + CallbackOnComplete)."""
        with self._cb_lock:
            self._next_key += 1
            key = self._next_key
            self._fns[key] = (fn, prop == ASYNC)
        ncv = len(const_vars)
        nmv = len(mutable_vars)
        cv = (ctypes.c_uint64 * max(ncv, 1))(*const_vars)
        mv = (ctypes.c_uint64 * max(nmv, 1))(*mutable_vars)
        op_id = ctypes.c_uint64()
        try:
            _native.check_call(self._lib.MXTPUEnginePush(
                self._h, self._trampoline, ctypes.c_void_p(key), cv, ncv,
                mv, nmv, prop, name.encode(), ctypes.byref(op_id)))
        except Exception:
            with self._cb_lock:
                self._fns.pop(key, None)
            raise
        return op_id.value

    def on_complete(self, op_id):
        """Complete an ASYNC op, releasing its var deps."""
        _native.check_call(self._lib.MXTPUEngineOnComplete(self._h, op_id))

    def on_complete_error(self, op_id, msg):
        _native.check_call(self._lib.MXTPUEngineOnCompleteError(
            self._h, op_id, str(msg).encode()))

    def _raise_with_op_traceback(self, err):
        with self._cb_lock:
            tb, self._last_op_error = self._last_op_error, None
        if tb:
            raise RuntimeError("%s\nop traceback:\n%s" % (err, tb)) from None
        raise err

    def wait_for_var(self, var):
        try:
            _native.check_call(self._lib.MXTPUEngineWaitForVar(self._h, var))
        except RuntimeError as e:
            self._raise_with_op_traceback(e)

    def wait_all(self):
        _native.check_call(self._lib.MXTPUEngineWaitAll(self._h))

    @property
    def num_pending(self):
        n = ctypes.c_int64()
        _native.check_call(self._lib.MXTPUEngineNumPending(self._h,
                                                           ctypes.byref(n)))
        return n.value


_engine = None
_engine_lock = threading.Lock()


def get():
    """Singleton engine (reference Engine::Get())."""
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                kind = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEngine")
                if kind != "NaiveEngine" and _native.available():
                    _engine = ThreadedEngine()
                else:
                    _engine = NaiveEngine()
    return _engine
