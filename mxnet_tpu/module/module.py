"""Module — symbolic training module over the executor group.

Reference: python/mxnet/module/module.py (Module:40, bind:364,
init_optimizer:474, update:644).  Gradient reduction across contexts
goes through the KVStore exactly like the reference (push/pull per
param); on TPU the 'tpu'/'device' kvstore resolves to mesh collectives.
"""

from __future__ import annotations

import logging

import numpy as _np

from .. import optimizer as opt
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import InitDesc, Uniform
from ..model import load_checkpoint
from ..ndarray import zeros
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup


def _create_kvstore(kvstore, num_device, arg_params):
    """reference: python/mxnet/model.py _create_kvstore."""
    from .. import kvstore as kvs

    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(_np.prod(p.shape) for p in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _live_params(param_names, param_arrays, grad_arrays):
    """Yield (position, name, per-device weights, per-device grads) for
    every parameter that was bound with a gradient — fixed params carry
    grad None and take no optimizer step."""
    for pos, name in enumerate(param_names):
        grads = grad_arrays[pos]
        if grads and grads[0] is not None:
            yield pos, name, param_arrays[pos], grads


def _grad_sync_through_kvstore(kvstore, param_names, param_arrays,
                               grad_arrays):
    """update_on_kvstore step: the store owns the optimizer, so one
    push(grads) / pull(weights) round-trip per parameter IS the update.
    Priority -position lets an async store overlap transfers in
    registration order — the wire protocol the reference's trainer
    speaks (python/mxnet/model.py _update_params_on_kvstore), kept
    because dist servers schedule by it."""
    for pos, name, weights, grads in _live_params(
            param_names, param_arrays, grad_arrays):
        kvstore.push(name, grads, priority=-pos)
        kvstore.pull(name, weights, priority=-pos)


def _local_update(updater, num_device, param_names, param_arrays,
                  grad_arrays, kvstore=None):
    """Host-side optimizer step.  A kvstore here only aggregates (push
    grads, pull back the sum); the updater then steps every (param,
    device) slot.  Slot keys pack as ``position * num_device + device``
    — optimizer state from save_optimizer_states/set_states is keyed by
    these ints (reference: python/mxnet/model.py _update_params), so
    the packing is observable API and pinned by the state round-trip
    tests."""
    for pos, name, weights, grads in _live_params(
            param_names, param_arrays, grad_arrays):
        if kvstore is not None:
            kvstore.push(name, grads, priority=-pos)
            kvstore.pull(name, grads, priority=-pos)
        for dev, (w, g) in enumerate(zip(weights, grads)):
            updater(pos * num_device + dev, g, w)


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = [current_context()]
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list
        self._group2ctxs = group2ctxs
        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + (state_names or [])
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """reference: module.py Module.load."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint

        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, *self.get_params())
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)

    # ------------------------------------------------------------- props
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.get_outputs()
        return list(zip(self._output_names, [o.shape for o in outs]))

    # ------------------------------------------------------------- params
    def get_params(self):
        assert self.binded or self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """reference: module.py init_params."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None and not (arg_params or aux_params):
            initializer = Uniform(0.01)

        if self._arg_params is None:
            self._arg_params = {
                name: zeros(self._exec_group.execs[0].arg_dict[name].shape,
                            dtype=self._exec_group.execs[0].arg_dict[name].dtype)
                for name in self._param_names}
        if self._aux_params is None:
            self._aux_params = {
                name: zeros(self._exec_group.execs[0].aux_dict[name].shape,
                            dtype=self._exec_group.execs[0].aux_dict[name].dtype)
                for name in self._aux_names}

        def _impl(name, arr, cache, desc):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    if cache_arr.shape != arr.shape:
                        raise MXNetError("shape mismatch for %s: %s vs %s"
                                         % (name, cache_arr.shape, arr.shape))
                    cache_arr.copyto(arr)
            elif initializer is not None:
                initializer(desc, arr)
            elif cache is not None and not allow_missing:
                raise MXNetError("%s is not presented" % name)

        attrs = self._symbol.attr_dict()
        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params, InitDesc(name, attrs.get(name, None)))
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params, InitDesc(name, attrs.get(name, None)))

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=allow_extra)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            return
        self._exec_group.set_params(arg_params, aux_params,
                                    allow_extra=allow_extra)
        self._arg_params = arg_params
        self._aux_params = aux_params
        self.params_initialized = True
        self._params_dirty = False

    # ------------------------------------------------------------- binding
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """reference: module.py bind:364."""
        if force_rebind:
            self._exec_group = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        def _norm(shapes):
            out = []
            for s in shapes:
                if hasattr(s, "name"):
                    out.append((s.name, tuple(s.shape)))
                else:
                    out.append((s[0], tuple(s[1])))
            return out

        self._data_shapes = _norm(data_shapes)
        self._label_shapes = _norm(label_shapes) if label_shapes else None

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad,
            shared_group=(shared_module._exec_group
                          if shared_module is not None else None),
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            state_names=self._state_names)
        if self.params_initialized:
            # params were set before bind (e.g. Module.load) — push to devices
            self._exec_group.set_params(self._arg_params, self._aux_params)
        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())

    # ------------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """reference: module.py init_optimizer:474."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {}
        if update_on_kvstore:
            idx2name.update(enumerate(self._exec_group.param_names))
        else:
            for k in range(len(self._context)):
                idx2name.update({i * len(self._context) + k: n
                                 for i, n in enumerate(self._exec_group.param_names)})
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but rescale_grad "
                    "is not normalized to 1.0/batch_size/num_workers (%s vs. %s).",
                    optimizer.rescale_grad, rescale_grad)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        """Share shared_module's optimizer/updater/kvstore — used by
        BucketingModule so every bucket updates through ONE optimizer
        state (reference: module.py borrow_optimizer:588)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # ------------------------------------------------------------- running
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """reference: module.py update:644."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        group = self._exec_group
        if self._update_on_kvstore:
            _grad_sync_through_kvstore(self._kvstore, group.param_names,
                                       group.param_arrays,
                                       group.grad_arrays)
        else:
            _local_update(self._updater, len(self._context),
                          group.param_names, group.param_arrays,
                          group.grad_arrays, kvstore=self._kvstore)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def get_states(self, merge_multi_context=True):
        """Values of the state inputs named by state_names (reference:
        module.py get_states — stateful RNN serving feeds these back
        through set_states between batches)."""
        assert self.binded and self.params_initialized
        return self._exec_group.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        self._exec_group.set_states(states=states, value=value)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._exec_group.update_metric(eval_metric, labels, pre_sliced)

    def _sync_params_from_devices(self):
        self._exec_group.get_params(self._arg_params, self._aux_params)
        if self._kvstore and self._update_on_kvstore:
            for param_name, param_val in sorted(self._arg_params.items()):
                if param_val.stype == "row_sparse":
                    row_ids = _np.arange(param_val.shape[0])
                    self._kvstore.row_sparse_pull(param_name, param_val,
                                                  row_ids=row_ids)
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            # atomic like every persistence path (docs/CHECKPOINTING.md)
            from ..checkpoint import atomic_write

            with atomic_write(fname) as tmp:
                with open(tmp, "wb") as fout:
                    fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            self._updater.set_states(open(fname, "rb").read())

    def install_monitor(self, mon):
        assert self.binded
        for ex in self._exec_group.execs:
            mon.install(ex)

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded

        def _norm(shapes):
            out = []
            for s in shapes:
                if hasattr(s, "name"):
                    out.append((s.name, tuple(s.shape)))
                else:
                    out.append((s[0], tuple(s[1])))
            return out

        self._data_shapes = _norm(data_shapes)
        self._label_shapes = _norm(label_shapes) if label_shapes else None
        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            self.for_training, self.inputs_need_grad,
            fixed_param_names=self._fixed_param_names)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)
