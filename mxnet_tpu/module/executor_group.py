"""DataParallelExecutorGroup — batch-sliced executors across contexts.

Reference: python/mxnet/module/executor_group.py:143 — splits each batch
across contexts (:303), runs per-device executors fwd/bwd, exposes
merged outputs.

TPU note: the production data-parallel path on TPU is a sharded batch
over the ICI mesh via kvstore='tpu' (one jit, XLA collectives) — see
mxnet_tpu/parallel/.  This group exists for API parity (multi-ctx
Module, tests ≈ test_multi_device_exec.py) and works over any jax
devices, including the virtual CPU mesh.
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, concatenate, zeros


def _split_input_slice(batch_size, work_load_list):
    """reference: python/mxnet/executor_manager.py _split_input_slice."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        end = batch_size if i == len(work_load_list) - 1 else \
            start + int(round(batch_size * w / total))
        slices.append(slice(start, end))
        start = end
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write",
                 state_names=None):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.state_names = state_names or []
        self.param_names = param_names
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = [d[0] for d in data_shapes]
        self.label_names = [l[0] for l in label_shapes] if label_shapes else []
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        self.batch_size = data_shapes[0][1][0]
        self.slices = _split_input_slice(self.batch_size, self.workload)
        self.execs = []
        self._default_execs = None
        self._shared_group = shared_group
        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                self.grad_req[name] = ("null" if name in self.fixed_param_names
                                       or not for_training else grad_req)
            elif name in self.data_names:
                self.grad_req[name] = grad_req if inputs_need_grad else "null"
            else:
                self.grad_req[name] = "null"
        self._bind_execs()

    def _bind_execs(self):
        self.execs = []
        for i, ctx in enumerate(self.contexts):
            sl = self.slices[i]
            n = sl.stop - sl.start
            shapes = {}
            for name, shape in self.data_shapes:
                shapes[name] = (n,) + tuple(shape[1:])
            for name, shape in (self.label_shapes or []):
                shapes[name] = (n,) + tuple(shape[1:])
            if self.state_names:
                # state inputs ride the data batch (reference: deferred
                # batch dim 0 in begin_state; our cells emit a concrete
                # stand-in of 1, re-batched here at bind time)
                for node in self.symbol._topo_nodes():
                    if node.is_variable and node.name in self.state_names \
                            and "__shape__" in node.attr_dict:
                        from ..symbol.symbol import _parse_attr_value

                        tail = tuple(_parse_attr_value(
                            node.attr_dict["__shape__"]))[1:]
                        shapes[node.name] = (n,) + tail
            ex = self.symbol.simple_bind(ctx=ctx, grad_req=self.grad_req,
                                         **shapes)
            if self._shared_group is not None \
                    and i < len(self._shared_group.execs):
                # share param STORAGE with the other group (reference:
                # executor_group shared_group / bucketing memory
                # sharing): the executors point at the same NDArray
                # objects, so updates through either module are visible
                # to both
                src = self._shared_group.execs[i]
                src_args = src.arg_dict
                src_aux = src.aux_dict
                for j, name in enumerate(ex._arg_names):
                    if name in self.param_names and name in src_args and \
                            tuple(src_args[name].shape) == \
                            tuple(ex.arg_arrays[j].shape):
                        ex.arg_arrays[j] = src_args[name]
                for j, name in enumerate(ex._aux_names):
                    if name in src_aux and tuple(src_aux[name].shape) == \
                            tuple(ex.aux_arrays[j].shape):
                        ex.aux_arrays[j] = src_aux[name]
            self.execs.append(ex)
        self.shared_data_arrays = [{} for _ in self.contexts]

    # --------------------------------------------------------------- params
    def set_params(self, arg_params, aux_params, allow_extra=False):
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params,
                                allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Average params over devices into the given dicts
        (reference: executor_group.get_params)."""
        for name in self.param_names:
            arrs = [ex.arg_dict[name] for ex in self.execs]
            out = arrs[0]
            if len(arrs) > 1:
                acc = arrs[0].asnumpy()
                for a in arrs[1:]:
                    acc = acc + a.asnumpy()
                arg_params[name][:] = acc / len(arrs)
            else:
                arg_params[name][:] = out
        for name in self.aux_names:
            arrs = [ex.aux_dict[name] for ex in self.execs]
            if len(arrs) > 1:
                acc = arrs[0].asnumpy()
                for a in arrs[1:]:
                    acc = acc + a.asnumpy()
                aux_params[name][:] = acc / len(arrs)
            else:
                aux_params[name][:] = arrs[0]

    # --------------------------------------------------------------- running
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        data = data_batch.data
        label = data_batch.label if data_batch.label is not None else []
        for i, ex in enumerate(self.execs):
            sl = self.slices[i]
            feed = {}
            for name, arr in zip(self.data_names, data):
                feed[name] = arr[sl]
            for name, arr in zip(self.label_names, label):
                feed[name] = arr[sl]
            ex.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        if not self.for_training:
            raise MXNetError("re-bind with for_training=True to run backward")
        for i, ex in enumerate(self.execs):
            og = None
            if out_grads is not None:
                sl = self.slices[i]
                og = [g[sl] for g in out_grads]
            ex.backward(out_grads=og)

    # --------------------------------------------------------------- states
    def get_states(self, merge_multi_context=True):
        """Current values of the state inputs (reference:
        executor_group.get_states; states are the symbol arguments named
        in state_names, carried across forwards by the caller)."""
        per_state = [[ex.arg_dict[n] for ex in self.execs]
                     for n in self.state_names]
        if not merge_multi_context:
            return per_state
        return [arrs[0] if len(arrs) == 1 else concatenate(arrs, axis=0)
                for arrs in per_state]

    def set_states(self, states=None, value=None):
        """Set state inputs from a states list (merged NDArray per state,
        or per-device lists as returned by get_outputs/get_states with
        merge_multi_context=False) or broadcast a scalar value
        (reference: executor_group.set_states)."""
        if (states is None) == (value is None):
            raise ValueError("set_states: exactly one of states/value")
        for si, name in enumerate(self.state_names):
            for di, ex in enumerate(self.execs):
                dst = ex.arg_dict[name]
                if value is not None:
                    dst[:] = value
                else:
                    src = states[si]
                    if isinstance(src, (list, tuple)):
                        dst[:] = src[di]
                    else:
                        dst[:] = src[self.slices[di]]

    def get_outputs(self, merge_multi_context=True):
        if not merge_multi_context or len(self.execs) == 1:
            outs = [[ex.outputs[i] for ex in self.execs]
                    for i in range(len(self.execs[0].outputs))]
            if merge_multi_context:
                return [o[0] for o in outs]
            return outs
        merged = []
        for i in range(len(self.execs[0].outputs)):
            merged.append(concatenate([ex.outputs[i] for ex in self.execs],
                                      axis=0))
        return merged

    def get_input_grads(self, merge_multi_context=True):
        grads = []
        for name in self.data_names:
            per_dev = [ex.grad_dict.get(name) for ex in self.execs]
            if merge_multi_context:
                grads.append(concatenate(per_dev, axis=0) if len(per_dev) > 1
                             else per_dev[0])
            else:
                grads.append(per_dev)
        return grads

    @property
    def grad_arrays(self):
        """grad_arrays[param_idx] = list of per-device grads
        (layout matches reference for kvstore consumption)."""
        out = []
        for name in self.param_names:
            out.append([ex.grad_dict[name] for ex in self.execs
                        if name in ex.grad_dict])
        return out

    @property
    def param_arrays(self):
        out = []
        for name in self.param_names:
            out.append([ex.arg_dict[name] for ex in self.execs])
        return out

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for i, ex in enumerate(self.execs):
            sl = self.slices[i]
            labels_slice = [l[sl] for l in labels] if not pre_sliced else labels[i]
            eval_metric.update(labels_slice, ex.outputs)
