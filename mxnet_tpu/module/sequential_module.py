"""SequentialModule — chain modules, feeding each one's outputs to the
next (reference: python/mxnet/module/sequential_module.py:28).
"""

from __future__ import annotations

import logging

from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    """Container chaining sub-modules; data flows through in order.

    ``add(module, take_labels=True, auto_wiring=True)`` appends a module;
    `take_labels` marks the module that consumes the training labels
    (reference meta keys META_TAKE_LABELS / META_AUTO_WIRING).
    """

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    def add(self, module, **kwargs):
        self._modules.append(module)
        for key in kwargs:
            assert key in (self.META_TAKE_LABELS, self.META_AUTO_WIRING), \
                "unknown meta %r" % (key,)
        self._metas.append(kwargs)
        # modifying the chain invalidates previous binding
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # ------------------------------------------------------------ props
    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for module in self._modules:
            module.init_params(initializer=initializer,
                               arg_params=arg_params, aux_params=aux_params,
                               allow_missing=True, force_init=force_init,
                               allow_extra=True)
        self.params_initialized = True

    # ---------------------------------------------------------- binding
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert shared_module is None, \
            "shared_module not supported for SequentialModule"
        assert self._modules, "add modules first"
        self._label_shapes = label_shapes

        my_data_shapes = data_shapes
        anybody_ever_needs_label = False
        for i_layer, (meta, module) in enumerate(zip(self._metas,
                                                     self._modules)):
            meta.setdefault(self.META_AUTO_WIRING, i_layer > 0)
            if meta.get(self.META_TAKE_LABELS):
                my_label_shapes = label_shapes
                anybody_ever_needs_label = True
            else:
                my_label_shapes = None
            my_inputs_need_grad = inputs_need_grad if i_layer == 0 else \
                for_training
            module.bind(data_shapes=my_data_shapes,
                        label_shapes=my_label_shapes,
                        for_training=for_training,
                        inputs_need_grad=my_inputs_need_grad,
                        force_rebind=force_rebind, grad_req=grad_req)
            if i_layer + 1 >= len(self._modules):
                break
            # compute this module's output shapes: via symbol inference
            # when it has one, else the module's own output_shapes
            # (PythonModule computes them from its bound data shapes)
            if getattr(module, "symbol", None) is not None:
                # entries may be (name, shape) tuples or DataDesc records
                shape_kwargs = {d[0]: tuple(d[1]) for d in my_data_shapes}
                _, out_shapes, _ = module.symbol.infer_shape(**shape_kwargs)
                outs = list(zip(module.output_names, out_shapes))
            else:
                outs = list(module.output_shapes)
            # auto_wiring on module i+1 = "rename my inputs from the
            # previous module's outputs"; defaults True for non-first
            # modules (they must get their data from somewhere)
            next_meta = self._metas[i_layer + 1]
            if next_meta.get(self.META_AUTO_WIRING, True):
                # rename outputs to the consumer's data names
                next_names = self._modules[i_layer + 1].data_names
                assert len(next_names) == len(outs), (
                    "module %d outputs %d arrays but module %d consumes %d"
                    % (i_layer, len(outs), i_layer + 1, len(next_names)))
                my_data_shapes = [(name, tuple(shape)) for name, (_, shape)
                                  in zip(next_names, outs)]
            else:
                my_data_shapes = [(name, tuple(shape))
                                  for name, shape in outs]
        if not anybody_ever_needs_label:
            self._label_shapes = None
        self.binded = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    # ---------------------------------------------------------- running
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        from ..io import DataBatch

        batch = data_batch
        for i_layer, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i_layer + 1 == len(self._modules):
                break
            out = module.get_outputs()
            next_names = self._modules[i_layer + 1].data_names
            batch = DataBatch(data=out,
                              label=data_batch.label,
                              pad=getattr(data_batch, "pad", 0))
            batch.provide_data = [(n, o.shape)
                                  for n, o in zip(next_names, out)]
            batch.provide_label = getattr(data_batch, "provide_label", None)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i_layer in range(len(self._modules) - 1, -1, -1):
            module = self._modules[i_layer]
            module.backward(out_grads=out_grads)
            if i_layer == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        for meta, module in zip(self._metas, self._modules):
            if meta.get(self.META_TAKE_LABELS):
                module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        for module in self._modules:
            module.install_monitor(mon)
