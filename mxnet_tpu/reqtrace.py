"""Per-request lifecycle tracing for the serving path (request x-ray).

The serving telemetry built through PR 17 is aggregate-only: the
``serve:*`` histograms say what the p99 queue wait *is*, but cannot
answer "why was *this* request slow?" — the question a serving fleet
is actually operated by.  This module gives every accepted request a
monotonic id and a compact lifecycle record written at the seams
``serving.py`` already has (submit → queue → batch-join → staging →
compute → scatter → done/rejected), carrying the bucket it rode, the
batch id, pad-row count, queue depth at submit, the worker that served
it, and the final outcome.

**Tail-based sampling.**  Recording every request at fleet qps would
drown the ring in healthy traffic, and head-sampling alone would miss
exactly the requests worth keeping.  So retention is decided at
*completion*: slow requests (above ``MXNET_TPU_REQTRACE_SLOW_MS``, or
above ``MXNET_TPU_REQTRACE_P99_MULT`` x the rolling p99 once the
latency window has warmed up), rejected requests, and NaN-sentinel
hits are ALWAYS retained; of the healthy rest, a deterministic 1-in-N
(``rid % N == 0``) survives as the baseline sample.  The same 1-in-N
head decision — made at submit, because span emission cannot wait for
the verdict — selects which requests also emit rank-tagged
chrome-trace spans, linked across the client/batcher/worker threads by
profiler *flow events* sharing ``id=rid``, so ``tools/diagnose.py
--merge-traces`` renders one request's journey through the pipeline.

Hot-path contract: callers guard on ``_state["on"]`` before calling a
feed (one dict read per request when disabled, pinned by
``test_bench_gate.py``); the feeds themselves are guard-first too
(mxlint ``DEFAULT_FEEDS``).  Retention math touches host floats only —
no sampling decision ever syncs a device value.  A request's record is
written sequentially along its lifecycle (the queue/condvar hand-offs
give happens-before), so only the ring, the rolling-latency window and
the outcome counters are shared — all mutated under ``_lock``.

Environment variables
---------------------
``MXNET_TPU_REQTRACE``          ``1`` enables from import (via the
    ``runtime_stats`` activation chain), ``0``/unset leaves it off.
``MXNET_TPU_REQTRACE_RING``     retained-record ring capacity
    (default 512).
``MXNET_TPU_REQTRACE_SAMPLE``   deterministic head-sample modulus N:
    ``rid % N == 0`` requests are kept and emit trace spans
    (default 16; ``1`` samples everything).
``MXNET_TPU_REQTRACE_SLOW_MS``  absolute slow threshold in ms; ``0``
    (default) defers to the rolling-p99 multiple alone.
``MXNET_TPU_REQTRACE_P99_MULT`` a completion is slow when its e2e
    exceeds this multiple of the rolling p99 (default 3.0; needs a
    warmed 64-sample window).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

from .log import get_logger

__all__ = ["enable", "disable", "is_enabled", "on_submit",
           "on_submitted", "on_reject", "on_join", "on_exec",
           "on_done", "snapshot", "exemplar", "reset"]

# window of recent e2e latencies backing the rolling p99 (and the
# minimum fill before the p99-multiple slow rule may fire)
WINDOW_CAP = 256
WINDOW_WARM = 64
P99_REFRESH = 32  # recompute the cached rolling p99 every N completions

# mxlint: disable=thread-shared-state -- single-key GIL-atomic enable flag; the guard-first contract forbids a lock on the disabled path
_state = {"on": False, "ring_cap": 512, "sample_n": 16, "slow_ms": 0.0,
          "p99_mult": 3.0, "p99_ms": None}
_lock = threading.Lock()
_RID = itertools.count(1)   # request ids (next() is GIL-atomic)
_BID = itertools.count(1)   # batch ids, assigned at batch-join
_RING: deque = deque(maxlen=512)      # retained records, under _lock
_WINDOW: deque = deque(maxlen=WINDOW_CAP)  # recent e2e ms, under _lock
_COUNTS: dict = {}                    # outcome -> count, under _lock
_TOTALS = {"seen": 0, "retained": 0, "dropped": 0}  # under _lock

_logger_cache: list = []


def _logger():
    if not _logger_cache:
        _logger_cache.append(get_logger("mxnet_tpu.reqtrace"))
    return _logger_cache[0]


def _env_int(name, default):
    try:
        return int(os.environ.get(name) or default)
    except (TypeError, ValueError):
        return int(default)


def _env_float(name, default):
    try:
        return float(os.environ.get(name) or default)
    except (TypeError, ValueError):
        return float(default)


# ------------------------------------------------------------ lifecycle


def enable(ring=None, sample=None, slow_ms=None, p99_mult=None):
    """Turn request tracing on.  Keyword overrides beat the env knobs;
    the ring is re-sized (existing retained records are kept when the
    capacity is unchanged)."""
    global _RING
    cap = _env_int("MXNET_TPU_REQTRACE_RING", 512) if ring is None \
        else int(ring)
    cap = max(1, cap)
    n = _env_int("MXNET_TPU_REQTRACE_SAMPLE", 16) if sample is None \
        else int(sample)
    n = max(1, n)
    slow = _env_float("MXNET_TPU_REQTRACE_SLOW_MS", 0.0) \
        if slow_ms is None else float(slow_ms)
    mult = _env_float("MXNET_TPU_REQTRACE_P99_MULT", 3.0) \
        if p99_mult is None else float(p99_mult)
    with _lock:
        if cap != _RING.maxlen:
            _RING = deque(_RING, maxlen=cap)
        _state["ring_cap"] = cap
        _state["sample_n"] = n
        _state["slow_ms"] = slow
        _state["p99_mult"] = mult
    _state["on"] = True


def disable():
    """Stop recording (retained records are kept; ``reset()`` drops
    them)."""
    _state["on"] = False


def is_enabled():
    return _state["on"]


def reset():
    """Disable and drop every record, counter and the id counters —
    a fixed workload replayed after ``reset()`` retains the identical
    rid set (the tail-sampling determinism contract, pinned in
    tests)."""
    global _RID, _BID
    _state["on"] = False
    with _lock:
        _RING.clear()
        _WINDOW.clear()
        _COUNTS.clear()
        _TOTALS["seen"] = 0
        _TOTALS["retained"] = 0
        _TOTALS["dropped"] = 0
        _state["p99_ms"] = None
    _RID = itertools.count(1)
    _BID = itertools.count(1)


# ------------------------------------------------------------ trace feeds


def _flow(ph, rid, ts=None):
    """Emit one chrome-trace flow event bound to ``id=rid`` on the
    calling thread (phases ``s``/``t``/``f`` with one id render as a
    single arrowed flow across threads in the trace viewer)."""
    from . import profiler as _profiler

    if not _profiler._state["running"]:
        return
    _profiler.add_event("request", cat="req", ph=ph, ts=ts, id=rid)


def _span(name, rid, dur_s, ts_end_us=None):
    """Emit a completed ``X`` span of ``dur_s`` seconds ending now (or
    at ``ts_end_us``) on the calling thread."""
    from . import profiler as _profiler

    if not _profiler._state["running"]:
        return
    dur_us = max(0.0, dur_s * 1e6)
    end = _profiler._now_us() if ts_end_us is None else ts_end_us
    _profiler.add_event(name, cat="req", ph="X", ts=end - dur_us,
                        dur=dur_us, args={"rid": rid})


def on_submit(req, depth):
    """Submit seam: assign the request id, open its lifecycle record
    (queue depth observed at submit), and make the deterministic head
    decision.  Runs on the client thread, before the batcher can see
    the request (the caller holds the server condvar), so every later
    seam finds ``req.trace`` set.  Deliberately touches NOTHING beyond
    the request object — the profiler must never be entered under the
    server condvar; :func:`on_submitted` emits the flow start after
    the caller releases it."""
    if not _state["on"]:
        return
    rid = next(_RID)
    head = (rid % _state["sample_n"] == 0)
    req.rid = rid
    req.trace = {"rid": rid, "n": req.n, "queue_depth": depth,
                 "head": head, "t_submit": req.t_submit,
                 "bucket": None, "batch": None, "worker": None,
                 "pad_rows": None, "outcome": None}


def on_submitted(req):
    """Flow-span tail of the submit seam — called on the client thread
    AFTER the server condvar is released (the profiler takes its own
    lock, and nesting it under the condvar would couple the two)."""
    if not _state["on"]:
        return
    tr = getattr(req, "trace", None)
    if tr is not None and tr["head"]:
        _flow("s", tr["rid"])


def on_reject(kind, n=0):
    """Rejection at the front door (queue-full / shape): the request
    never enters the pipeline, but it must not vanish from accounting —
    record a degenerate always-retained lifecycle with the reject kind
    as its outcome."""
    if not _state["on"]:
        return
    rid = next(_RID)
    rec = {"rid": rid, "n": n, "queue_depth": None, "head": False,
           "bucket": None, "batch": None, "worker": None,
           "pad_rows": None, "outcome": kind, "retained": kind,
           "e2e_ms": 0.0, "queue_ms": None, "stage_ms": None,
           "compute_ms": None, "scatter_ms": None}
    with _lock:
        _TOTALS["seen"] += 1
        _TOTALS["retained"] += 1
        _COUNTS[kind] = _COUNTS.get(kind, 0) + 1
        _RING.append(rec)


def on_join(reqs, bucket):
    """Batch-join seam (batcher thread): stamp the bucket and a fresh
    batch id on every member, emit the queue-wait span + flow step for
    head-sampled members."""
    if not _state["on"]:
        return
    bid = next(_BID)
    for r in reqs:
        tr = getattr(r, "trace", None)
        if tr is None:
            continue
        tr["bucket"] = bucket
        tr["batch"] = bid
        tr["t_batched"] = r.t_batched
        if tr["head"]:
            _span("req:queue", tr["rid"], r.t_batched - tr["t_submit"])
            _flow("t", tr["rid"])


def on_exec(reqs, worker, pad_rows, t_staged, t_compute):
    """Execution seam (worker thread, once per batch after the fetch
    host-sync): stamp the worker, the batch's pad-row count and the
    staging/compute boundary times on every member's record."""
    if not _state["on"]:
        return
    for r in reqs:
        tr = getattr(r, "trace", None)
        if tr is None:
            continue
        tr["worker"] = worker
        tr["pad_rows"] = pad_rows
        tr["t_staged"] = t_staged
        tr["t_compute"] = t_compute


def on_done(req, outcome, t_done=None):
    """Completion seam (worker thread): finalize the record — derive
    the per-seam millisecond ladder, make the tail retention decision
    (always keep non-``ok`` outcomes and slow completions, else the
    deterministic head sample), and close the flow for head-sampled
    requests."""
    if not _state["on"]:
        return
    tr = getattr(req, "trace", None)
    if tr is None:
        return
    now = time.perf_counter() if t_done is None else t_done
    t_submit = tr.pop("t_submit")
    t_batched = tr.pop("t_batched", None)
    t_staged = tr.pop("t_staged", None)
    t_compute = tr.pop("t_compute", None)
    e2e_ms = (now - t_submit) * 1e3
    tr["e2e_ms"] = e2e_ms
    tr["queue_ms"] = None if t_batched is None \
        else (t_batched - t_submit) * 1e3
    tr["stage_ms"] = None if t_staged is None or t_batched is None \
        else (t_staged - t_batched) * 1e3
    tr["compute_ms"] = None if t_compute is None or t_staged is None \
        else (t_compute - t_staged) * 1e3
    tr["scatter_ms"] = None if t_compute is None \
        else (now - t_compute) * 1e3
    tr["outcome"] = outcome
    slow_ms = _state["slow_ms"]
    mult = _state["p99_mult"]
    with _lock:
        _TOTALS["seen"] += 1
        _COUNTS[outcome] = _COUNTS.get(outcome, 0) + 1
        _WINDOW.append(e2e_ms)
        if _state["p99_ms"] is None \
                or _TOTALS["seen"] % P99_REFRESH == 0:
            w = sorted(_WINDOW)
            _state["p99_ms"] = w[min(len(w) - 1,
                                     int(len(w) * 0.99))]
        p99 = _state["p99_ms"]
        why = None
        if outcome != "ok":
            why = outcome
        elif slow_ms and e2e_ms >= slow_ms:
            why = "slow"
        elif p99 is not None and len(_WINDOW) >= WINDOW_WARM \
                and e2e_ms >= mult * p99:
            why = "slow"
        elif tr["head"]:
            why = "head"
        if why is None:
            _TOTALS["dropped"] += 1
        else:
            tr["retained"] = why
            _TOTALS["retained"] += 1
            _RING.append(tr)
    if tr["head"]:
        # spans/flows outside _lock: the profiler takes its own lock
        if t_batched is not None:
            _span("req:exec", tr["rid"], now - t_batched)
        _flow("f", tr["rid"])


# ------------------------------------------------------------- snapshots


def snapshot():
    """JSON-ready view: sampling config, totals, per-outcome counts,
    the rolling p99 and every retained record (oldest first)."""
    with _lock:
        ring = [dict(r) for r in _RING]
        counts = dict(_COUNTS)
        totals = dict(_TOTALS)
        p99 = _state["p99_ms"]
    if not _state["on"] and not totals["seen"]:
        return {"enabled": False}
    return {"enabled": _state["on"], "ring_cap": _state["ring_cap"],
            "sample_n": _state["sample_n"],
            "slow_ms": _state["slow_ms"],
            "p99_mult": _state["p99_mult"], "rolling_p99_ms": p99,
            "seen": totals["seen"], "retained": totals["retained"],
            "dropped": totals["dropped"], "by_outcome": counts,
            "ring": ring}


def exemplar():
    """``(rid, e2e_seconds)`` of the slowest retained completion — the
    exemplar the ``serve:*`` Prometheus summaries attach — or None."""
    with _lock:
        worst = None
        for r in _RING:
            e2e = r.get("e2e_ms")
            if e2e and (worst is None or e2e > worst["e2e_ms"]):
                worst = r
    if worst is None:
        return None
    return (worst["rid"], worst["e2e_ms"] / 1e3)


def _activate_from_env():
    """Import-time arming — called by ``runtime_stats`` once its module
    globals exist (before the autopilot, which must arm last)."""
    flag = os.environ.get("MXNET_TPU_REQTRACE")
    if not flag or flag == "0":
        return False
    enable()
    return True
