"""Runtime feature detection (reference: include/mxnet/libinfo.h:134,
src/libinfo.cc, python/mxnet/runtime.py).

Features reflect what this build actually supports: TPU/XLA in place of
CUDA/CUDNN, etc.  Queryable the same way: ``mx.runtime.Features()``.
"""

from __future__ import annotations


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return "%s %s" % ("✔" if self.enabled else "✖", self.name)


def _detect():
    feats = {}

    def add(name, enabled):
        feats[name] = Feature(name, bool(enabled))

    try:
        import jax

        has_jax = True
        try:
            platforms = {d.platform for d in jax.devices()}
        except RuntimeError:
            platforms = set()
    except ImportError:  # pragma: no cover
        has_jax = False
        platforms = set()
    add("TPU", bool(platforms - {"cpu"}))
    add("XLA", has_jax)
    add("PALLAS", has_jax)
    add("CUDA", False)
    add("CUDNN", False)
    add("NCCL", False)
    add("MKLDNN", False)
    add("OPENCV", _has("cv2"))
    add("PIL", _has("PIL"))
    add("BLAS_OPEN", True)
    add("LAPACK", True)
    add("F16C", True)
    add("BF16", True)
    add("DIST_KVSTORE", True)
    add("INT64_TENSOR_SIZE", True)
    add("SIGNAL_HANDLER", False)
    add("PROFILER", True)
    add("NATIVE_IO", _has_native())
    return feats


def _has(mod):
    import importlib.util

    return importlib.util.find_spec(mod) is not None


def _has_native():
    import os

    return os.path.exists(os.path.join(os.path.dirname(__file__), "native",
                                       "libmxtpu.so"))


class Features(dict):
    """Map of feature name → Feature (reference: runtime.Features)."""

    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, feature_name):
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
