"""Step-time attribution — where each training step's wall time goes.

``profiler.py`` records raw spans and ``histogram.py`` records raw
latency distributions; neither answers the first question of every perf
investigation: *which phase of the step is the time in?*  This module
decomposes the wall time between consecutive ``Trainer.step`` returns
(one full iteration: data wait + forward/backward + reduce + update)
into the canonical phases below, with an explicit **unattributed
remainder** — so the breakdown always sums to the step wall time and
never silently over-claims (arXiv:2301.13062's fusion/idle-gap lens,
applied host-side).

Phases (:data:`PHASES`; shared vocabulary with ``tools/diagnose.py
--doctor`` and ``tools/profile_step.py`` — same names, ms units):

- ``data_wait``        ``DataIter.__next__`` (batch assembly / input wait)
- ``forward``          the ``autograd.record()`` region / symbolic
  ``executor:forward`` (exclusive of nested dispatch/compile feeds)
- ``backward``         ``autograd.backward`` / ``executor:backward``
- ``dispatch_warm``    cache-warm op dispatch wall time
- ``compile``          jit-cache-miss wall time (trace + XLA compile)
- ``compiled_step``    the one warm whole-step program call
  (``compiled_step.py``: fused fwd+bwd+update; its build/compile time
  lands in ``compile``)
- ``kvstore``          allreduce / kvstore push+pull (incl. dist RTT)
- ``optimizer_update`` worker-side optimizer update
- ``checkpoint_write`` in-step checkpoint snapshot (the async capture,
  or the full write in ``MXNET_TPU_CKPT_ASYNC=0`` mode)
- ``health_drain``     numerics-health queue drain (the layer's one sync)

Leaf phases accumulate measured durations directly (``compiled_step``
is a leaf: the one warm whole-step call, timed by ``compiled_step.py``
whenever dispatch timing is on); container phases
(``forward``, ``backward``, ``kvstore``, ``optimizer_update``,
``data_wait``, ``checkpoint_write``)
record their wall time **exclusive** of any attribution that landed
inside their window (:func:`begin`/:func:`end` snapshot the running
attributed total), so a warm op dispatch inside an allreduce is counted
once, under ``dispatch_warm`` — phase sums stay disjoint and their
total can never exceed the step wall.

Collection contract matches ``runtime_stats``/``histogram``: all
mutation is GIL-atomic dict arithmetic on the training thread, feeding
sites guard on ``_state["on"]`` *before* taking timestamps, and the
disabled path is one dict read (bench-gated in
``tests/test_bench_gate.py``).  Counts are exact for the reference
single-training-thread loop and best-effort under concurrency.

Per-phase per-step values land in private ``histogram.Histogram``
instances, so :func:`snapshot` carries full distributions (p50/p90/p99)
that merge associatively — ``runtime_stats.compare`` diffs them between
two diag dumps and the perf doctor ranks bottlenecks from the shares.

Environment variables
---------------------
``MXNET_TPU_STEPSTATS``  ``1`` enables attribution from import, ``0``
    forces it off; unset, it auto-enables when ``MXNET_TPU_PROFILE`` or
    ``MXNET_TPU_DIAG`` is set (those runs already pay for timestamps,
    and the diag dump should carry a populated "Step anatomy").
"""

from __future__ import annotations

import os
import time

from .histogram import Histogram

__all__ = ["PHASES", "PHASE_LABELS", "enable", "disable", "is_enabled",
           "add", "begin", "end", "end_step", "snapshot", "anatomy",
           "device_anatomy_ms", "render", "reset"]

# canonical phase vocabulary, in render order.  The perf doctor
# (tools/diagnose.py --doctor), runtime_stats.compare, and
# tools/profile_step.py all name phases from this table so a finding,
# a diff row, and a measured-trace column agree on names and units.
PHASES = ("data_wait", "forward", "backward", "dispatch_warm", "compile",
          "compiled_step", "kvstore", "optimizer_update",
          "checkpoint_write", "health_drain")

PHASE_LABELS = {
    "data_wait": "data wait (io:next_batch)",
    "forward": "forward (autograd:record)",
    "backward": "backward (autograd:backward)",
    "dispatch_warm": "warm dispatch",
    "compile": "compile (jit-cache miss)",
    "compiled_step": "compiled whole-step call",
    "kvstore": "allreduce / kvstore",
    "optimizer_update": "optimizer update",
    "checkpoint_write": "checkpoint snapshot",
    "health_drain": "health drain",
    # device-trace phases (tools/profile_step.py's measured anatomy)
    "device_compute": "device compute (HLO)",
    "hbm_prefetch": "HBM prefetch (overlapped)",
    "unattributed": "unattributed remainder",
}

_state = {"on": False}
# phase -> seconds accumulated since the last step boundary
_window: dict = {}
# "attr": total attributed seconds in the current window (what
# containers subtract); "boundary": perf_counter of the last step end
_cur = {"attr": 0.0, "boundary": None}
# "steps": closed step windows; "overattributed": windows whose
# attribution exceeded the measured wall (clock noise / cross-thread
# feeds) — remainder clamped to 0 and the event counted, never hidden
# mxlint: disable=thread-shared-state -- single-writer by contract: end_step runs on the training thread between steps
_agg = {"steps": 0, "overattributed": 0, "last": None}
# per-phase per-step distributions + "wall" + "unattributed"
_HISTS: dict = {}

_perf_counter = time.perf_counter


def enable():
    """Turn attribution on; also raises the dispatch layer's cache-warm
    timing flag (``runtime_stats.DIAG_TIMING``) so the ``dispatch_warm``
    and ``compile`` phases have a feed without the profiler running."""
    _state["on"] = True
    from . import runtime_stats as _rts

    _rts.DIAG_TIMING = True


def disable():
    """Turn attribution off (accumulated anatomy is kept; ``reset()``
    drops it).  Dispatch timing reverts to its env/histogram-derived
    state."""
    _state["on"] = False
    from . import histogram as _histogram
    from . import runtime_stats as _rts

    _rts.DIAG_TIMING = bool(os.environ.get("MXNET_TPU_DIAG")) \
        or _histogram._state["on"]


def is_enabled():
    return _state["on"]


# ------------------------------------------------------------ hot path


def add(phase, seconds):
    """Leaf feed: attribute ``seconds`` of the current step window to
    ``phase``.  Callers guard on ``_state["on"]`` before taking their
    timestamps; this re-check makes a mid-window disable safe."""
    if not _state["on"]:
        return
    _window[phase] = _window.get(phase, 0.0) + seconds
    _cur["attr"] += seconds


def begin():
    """Open a container-phase window: returns an opaque token for
    :func:`end`.  Container phases record their wall time exclusive of
    everything attributed inside them (nested leaf/container feeds), so
    phase sums stay disjoint."""
    return (_perf_counter(), _cur["attr"])


def end(phase, token):
    """Close a container-phase window opened by :func:`begin`."""
    if not _state["on"] or token is None:
        return
    wall = _perf_counter() - token[0]
    nested = _cur["attr"] - token[1]
    excl = wall - nested
    if excl > 0.0:
        _window[phase] = _window.get(phase, 0.0) + excl
        _cur["attr"] += excl


def _hist(name):
    h = _HISTS.get(name)
    if h is None:
        h = _HISTS[name] = Histogram()
    return h


def end_step():
    """Close the current step window (called by ``Trainer.step`` after
    the checkpoint hook).  The first boundary only arms the clock — the
    partial warmup window before it (model init, first compiles before
    any step completed) is discarded, so every recorded window spans
    exactly one full iteration."""
    if not _state["on"]:
        return
    now = _perf_counter()
    boundary = _cur["boundary"]
    _cur["boundary"] = now
    window = dict(_window)
    _window.clear()
    _cur["attr"] = 0.0
    if boundary is None:
        return
    wall = now - boundary
    attributed = sum(window.values())
    remainder = wall - attributed
    if remainder < 0.0:
        _agg["overattributed"] += 1
        remainder = 0.0
    _agg["steps"] += 1
    _hist("wall").observe(wall)
    for p in PHASES:
        _hist(p).observe(window.get(p, 0.0))
    _hist("unattributed").observe(remainder)
    last = {"wall": wall, "unattributed": remainder}
    last.update(window)
    _agg["last"] = last


# ----------------------------------------------------------- read side


def snapshot():
    """JSON-ready view: ``{"enabled", "steps", "overattributed",
    "wall": hist, "phases": {phase: hist}, "unattributed": hist,
    "last": {...}}`` (histogram snapshots merge associatively — the
    dump-diff and cluster machinery rely on it).  Empty when no step
    window has closed yet."""
    out = {"enabled": _state["on"], "steps": _agg["steps"],
           "overattributed": _agg["overattributed"]}
    if _agg["steps"]:
        out["wall"] = _hist("wall").snapshot()
        out["phases"] = {p: _HISTS[p].snapshot()
                         for p in PHASES if p in _HISTS}
        out["unattributed"] = _hist("unattributed").snapshot()
        if _agg["last"] is not None:
            out["last"] = dict(_agg["last"])
    return out


def _ms(v):
    return None if v is None else v * 1e3


def anatomy(snap=None):
    """Derived per-step anatomy from a :func:`snapshot` (live when
    omitted): ``{"steps", "step_wall_ms": {mean,p50,p99,sum},
    "phases": {phase: {mean_ms,p50_ms,p99_ms,share}},
    "unattributed": {...}}`` where ``share`` is the phase's fraction of
    the summed step wall time.  The shared currency of ``report()``'s
    "Step anatomy" table, the perf doctor's ranking, and
    ``runtime_stats.compare``."""
    snap = snapshot() if snap is None else snap
    steps = snap.get("steps", 0)
    if not steps:
        return {"steps": 0, "phases": {}}
    wall = snap.get("wall") or {}
    wall_sum = wall.get("sum") or 0.0

    def _derive(h):
        total = h.get("sum") or 0.0
        return {"mean_ms": _ms(h.get("mean")), "p50_ms": _ms(h.get("p50")),
                "p99_ms": _ms(h.get("p99")), "sum_ms": _ms(total),
                "share": (total / wall_sum) if wall_sum else 0.0}

    phases = {p: _derive(h)
              for p, h in (snap.get("phases") or {}).items()}
    return {"steps": steps,
            "step_wall_ms": {"mean_ms": _ms(wall.get("mean")),
                             "p50_ms": _ms(wall.get("p50")),
                             "p99_ms": _ms(wall.get("p99")),
                             "sum_ms": _ms(wall_sum)},
            "phases": phases,
            "unattributed": _derive(snap.get("unattributed") or {}),
            "overattributed": snap.get("overattributed", 0)}


def device_anatomy_ms(step_wall_ms, phases_ms):
    """Shape a measured device-trace breakdown (``tools/profile_step.py``)
    into the same anatomy structure the host-side phases use: ``{
    "step_wall_ms", "phases_ms": {phase: ms}, "unattributed_ms"}`` with
    the explicit-remainder convention (``unattributed`` clamped to 0;
    when async device phases overlap the wall and sum past it, the
    excess is reported as ``overlap_ms`` instead of being hidden).
    Phase keys should come from :data:`PHASE_LABELS` so the doctor and
    the tool agree on names and units."""
    phases = {k: round(float(v), 3) for k, v in phases_ms.items()
              if v and v > 0.0}
    attributed = sum(phases.values())
    wall = round(float(step_wall_ms), 3)
    out = {"step_wall_ms": wall,
           "phases_ms": phases,
           "unattributed_ms": round(max(0.0, wall - attributed), 3)}
    if attributed > wall:
        out["overlap_ms"] = round(attributed - wall, 3)
    return out


def render(snap=None):
    """Text table for the "Step anatomy" section of ``report()`` /
    diag-dump pretty-printing."""
    snap = snapshot() if snap is None else snap
    lines = ["", "Step anatomy (per-step phase attribution, ms)"]
    if not snap or not snap.get("steps"):
        lines.append("(no step windows closed — stepstats.enable() or "
                     "MXNET_TPU_STEPSTATS=1; auto-on under "
                     "MXNET_TPU_PROFILE / MXNET_TPU_DIAG)")
        return lines
    a = anatomy(snap)

    def _fmt(v):
        return "-" if v is None else "%.3f" % v

    lines.append("%d step window(s)%s" % (
        a["steps"],
        "" if not a.get("overattributed") else
        " (%d over-attributed; remainder clamped to 0)"
        % a["overattributed"]))
    lines.append("%-28s %8s %9s %9s %9s %7s"
                 % ("Phase", "Share", "Mean", "p50", "p99", "Sum(s)"))
    w = a["step_wall_ms"]
    lines.append("%-28s %8s %9s %9s %9s %7.3f"
                 % ("step wall", "100.0%", _fmt(w["mean_ms"]),
                    _fmt(w["p50_ms"]), _fmt(w["p99_ms"]),
                    (w["sum_ms"] or 0.0) / 1e3))
    rows = [(p, a["phases"][p]) for p in PHASES if p in a["phases"]]
    rows.append(("unattributed", a["unattributed"]))
    for p, d in rows:
        lines.append("%-28s %7.1f%% %9s %9s %9s %7.3f"
                     % (PHASE_LABELS.get(p, p)[:28], d["share"] * 100.0,
                        _fmt(d["mean_ms"]), _fmt(d["p50_ms"]),
                        _fmt(d["p99_ms"]), (d["sum_ms"] or 0.0) / 1e3))
    return lines


def reset():
    """Drop every accumulator and re-open the warmup window (tests)."""
    _window.clear()
    _cur["attr"] = 0.0
    _cur["boundary"] = None
    _agg["steps"] = 0
    _agg["overattributed"] = 0
    _agg["last"] = None
    _HISTS.clear()


def _activate_from_env():
    """Import-time arming — called by ``runtime_stats`` once its module
    globals exist (enable() writes ``runtime_stats.DIAG_TIMING``)."""
    flag = os.environ.get("MXNET_TPU_STEPSTATS")
    if flag == "0":
        return False
    if flag == "1" or os.environ.get("MXNET_TPU_PROFILE") \
            or os.environ.get("MXNET_TPU_DIAG"):
        enable()
        return True
    return False
