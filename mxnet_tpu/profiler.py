"""Profiler — chrome://tracing output + aggregate stats.

Reference: src/profiler/profiler.h:256 (Profiler singleton, ProfileStat
arrays, chrome-tracing JSON dump :87,437), aggregate_stats.cc,
python/mxnet/profiler.py:33 (set_config/set_state/dump, custom
domains/tasks/counters/markers).

TPU-native: two layers. (1) A Python-side event recorder with the same
API (set_config/set_state/dump/dumps, Domain/Task/Frame/Counter/Marker)
producing chrome-tracing JSON — this traces the *framework* (op
dispatch, iterator, kvstore). (2) ``start_xla_trace``/``stop_xla_trace``
wrap ``jax.profiler`` for device-side traces viewable in TensorBoard /
Perfetto — the analog of the reference's device-level opr profiling,
since XLA owns kernel timing on TPU.

Distributed telemetry (PR 7): under a ``tools/launch.py`` job every
event carries a rank-tagged pid (worker rank, or 10000 + shard id for
servers), the dumped JSON gains ``process_name``/``process_sort_index``
metadata plus an ``mxtpu`` header — role/rank, a perf-counter →
wall-clock anchor pair captured at import, and the kvstore-ping clock
offset (``set_clock_offset``; ``DistAsyncKVStore.estimate_clock_offset``)
— and :func:`merge_traces` folds several ranks' files into ONE
chrome trace on a common timeline, so the whole cluster's step anatomy
renders in a single viewer.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .log import process_identity, rank_suffix_path

_state = {
    "config": {"profile_all": False, "profile_symbolic": True,
               "profile_imperative": True, "profile_memory": False,
               "profile_api": False, "aggregate_stats": False,
               "filename": "profile.json"},
    "running": False,
    "events": [],
    "lock": threading.Lock(),
    "xla_dir": None,
    # estimated wall-clock offset of this process vs PS shard 0
    # (seconds; set_clock_offset) — merge_traces subtracts it
    "clock_offset": None,
}

# rank-tagged trace pid: distinct per role/rank so merged traces show
# one labelled track per process (servers offset far above any worker
# rank).  Single-process runs keep the historical pid 0.
_IDENTITY = process_identity()
TRACE_PID = 0 if _IDENTITY is None else (
    _IDENTITY["rank"] if _IDENTITY["role"] != "server"
    else 10000 + _IDENTITY["rank"])

# perf_counter↔wall anchor pair, captured back-to-back at import: event
# timestamps are perf_counter µs (monotonic, per-process epoch), so
# cross-process merging needs each file to say where its epoch sits on
# the wall clock
_ANCHOR = (time.perf_counter_ns() / 1000.0, time.time() * 1e6)


def set_clock_offset(offset_seconds):
    """Record this process's estimated wall-clock offset (seconds)
    relative to the cluster reference clock (PS shard 0) — stamped into
    the trace header for :func:`merge_traces`."""
    _state["clock_offset"] = float(offset_seconds)


# mxlint: disable=thread-shared-state -- startup publication, set once
_kvstore_handle = None


def set_kvstore_handle(kv):
    """Register the kvstore used to reach parameter-server processes
    (reference: profiler.py set_kvstore_handle — enables
    profile_process='server')."""
    global _kvstore_handle
    _kvstore_handle = kv


def _server_command(fn, kwargs):
    import json as _json

    if _kvstore_handle is None:
        raise ValueError("profile_process='server' needs "
                         "profiler.set_kvstore_handle(kv) first")
    _kvstore_handle._send_command_to_servers(
        "profiler", _json.dumps({"fn": fn, "kwargs": kwargs}))


def set_config(**kwargs):
    """reference: profiler.py:33 set_config.  With
    profile_process='server' the config is forwarded to every
    parameter-server process (reference: KVStoreServerProfilerCommand,
    include/mxnet/kvstore.h:49)."""
    if kwargs.pop("profile_process", "worker") == "server":
        return _server_command("set_config", kwargs)
    _state["config"].update(kwargs)


def set_state(state="stop", profile_process="worker"):
    """'run' | 'stop' (reference: profiler.py:89)."""
    if profile_process == "server":
        return _server_command("set_state", {"state": state})
    if state == "run":
        _state["running"] = True
    elif state == "stop":
        _state["running"] = False
    else:
        raise ValueError("state must be 'run' or 'stop'")


def is_running():
    return _state["running"]


def _now_us():
    return time.perf_counter_ns() / 1000.0


def add_event(name, cat, ph, ts=None, pid=None, tid=None, args=None,
              dur=None, id=None):
    if not _state["running"]:
        return
    ev = {"name": name, "cat": cat, "ph": ph,
          "ts": ts if ts is not None else _now_us(),
          "pid": TRACE_PID if pid is None else pid,
          "tid": tid if tid is not None else threading.get_ident()}
    if args:
        ev["args"] = args
    if dur is not None:
        ev["dur"] = dur
    if id is not None:
        # flow-event binding ("s"/"t"/"f" sharing one id render as a
        # single arrowed flow across threads/processes)
        ev["id"] = id
        if ph in ("s", "t", "f"):
            ev["bp"] = "e"
    with _state["lock"]:
        _state["events"].append(ev)


class scope:
    """``with profiler.scope('fwd'):`` records a complete event."""

    __slots__ = ("name", "cat", "args", "t0")

    def __init__(self, name, cat="framework", args=None):
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0 = _now_us()
        return self

    def __exit__(self, *a):
        add_event(self.name, self.cat, "X", ts=self.t0,
                  dur=_now_us() - self.t0, args=self.args)
        return False


class _NullSpan:
    """Shared do-nothing context manager: the disabled-profiler fast
    path of :func:`span` — no allocation, no timestamps."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_SPAN = _NullSpan()


def span(name, cat="framework", args=None):
    """Guard-first complete-event span for framework hot loops.

    Returns a shared no-op when the profiler is not recording, so
    instrumented code pays one flag check and no event/span allocation
    when telemetry is off (the hard constraint of PR 2's tentpole).
    Exceptions propagate; the event is still recorded."""
    if not _state["running"]:
        return _NULL_SPAN
    return scope(name, cat, args)


def counter(name, values, cat="framework"):
    """Guard-first chrome-trace counter ("C") event: one flag check and
    nothing else while the profiler is off.  ``values`` is the
    ``{series: number}`` args dict — the per-step telemetry sinks
    (device-memory timeline, numerics-health ``grad_norm`` /
    ``nan_total``) emit through this."""
    if not _state["running"]:
        return
    add_event(name, cat, "C", args=values)


def _identity_meta():
    """chrome-trace metadata events naming this process's track, plus
    the ``mxtpu`` header dict :func:`merge_traces` aligns clocks with.
    Uses the SAME import-time identity as ``TRACE_PID`` — events are
    already tagged with it, so a header from a fresh env read could
    name a rank whose pid no event carries."""
    ident = _IDENTITY
    if ident is not None:
        pname = "%s %d (pid %d)" % (ident["role"], ident["rank"],
                                    os.getpid())
    else:
        pname = "process %d" % os.getpid()
    meta = [
        {"name": "process_name", "ph": "M", "pid": TRACE_PID,
         "args": {"name": pname}},
        {"name": "process_sort_index", "ph": "M", "pid": TRACE_PID,
         "args": {"sort_index": TRACE_PID}},
    ]
    header = {"role": ident["role"] if ident else None,
              "rank": ident["rank"] if ident else None,
              "pid": os.getpid(), "trace_pid": TRACE_PID,
              "perf_anchor_us": _ANCHOR[0], "wall_anchor_us": _ANCHOR[1],
              "clock_offset_us": None if _state["clock_offset"] is None
              else _state["clock_offset"] * 1e6}
    return meta, header


def dump(finished=True, profile_process="worker"):
    """Write chrome-tracing JSON; returns the absolute path.

    ``finished=True`` also stops recording (reference semantics:
    profiler.py dump's `finished` finalizes the profiler).  The file
    carries rank-tagged process metadata and the ``mxtpu`` clock
    header, so per-rank files are :func:`merge_traces`-ready."""
    if profile_process == "server":
        return _server_command("dump", {"finished": finished})
    if finished:
        _state["running"] = False
    fname = _state["config"].get("filename", "profile.json")
    with _state["lock"]:
        events = list(_state["events"])
    meta, header = _identity_meta()
    with open(fname, "w") as f:
        # metadata trails the real events: chrome accepts "M" records
        # anywhere, and readers that index traceEvents[0] keep seeing a
        # timestamped span
        json.dump({"traceEvents": events + meta,
                   "displayTimeUnit": "ms", "mxtpu": header}, f)
    return os.path.abspath(fname)


def merge_traces(paths, out="merged_trace.json"):
    """Merge per-rank chrome traces into ONE file on a shared timeline.

    Each input's event timestamps are per-process ``perf_counter`` µs;
    using the file's ``mxtpu`` header they are re-based onto the wall
    clock (anchor pair) minus the rank's kvstore-ping clock offset, so
    spans line up across machines to within the ping RTT/2.  Files
    without a header (pre-PR-7, or hand-made) are kept on their own
    epoch.  Colliding pids between files are remapped to keep one
    track per process; the merged timeline is normalized to start at
    t=0.  Returns the absolute output path."""
    merged = []
    used_pids: set = set()
    sources = []
    for path in paths:
        with open(path) as f:
            data = json.load(f)
        events = data.get("traceEvents", [])
        header = data.get("mxtpu") or {}
        shift = 0.0
        if header.get("perf_anchor_us") is not None:
            # event ts (per-process perf µs) → this process's wall
            # clock (anchor pair) → the reference clock: offset is
            # server_minus_this (PSClient.ping), so reference time =
            # local wall + offset — ADD it
            shift = header["wall_anchor_us"] - header["perf_anchor_us"] \
                + (header.get("clock_offset_us") or 0.0)
        pids = {ev.get("pid", 0) for ev in events}
        remap = {}
        for p in sorted(pids):
            new = p
            while new in used_pids:
                new += 100000  # far past any rank/server tag
            remap[p] = new
            used_pids.add(new)
        for ev in events:
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift
            ev["pid"] = remap.get(ev.get("pid", 0), ev.get("pid", 0))
            merged.append(ev)
        sources.append({"path": os.path.abspath(path),
                        "role": header.get("role"),
                        "rank": header.get("rank"),
                        "trace_pids": sorted(remap.values()),
                        "clock_offset_us": header.get("clock_offset_us")})
    timed = [ev["ts"] for ev in merged if "ts" in ev]
    if timed:
        t0 = min(timed)
        for ev in merged:
            if "ts" in ev:
                ev["ts"] -= t0
    with open(out, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms",
                   "mxtpu": {"merged_from": sources}}, f)
    return os.path.abspath(out)


def dumps(reset=False):
    """In-memory aggregate table (reference: aggregate_stats.cc)."""
    with _state["lock"]:
        events = list(_state["events"])
        if reset:
            _state["events"] = []
    agg = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        st = agg.setdefault(ev["name"], {"count": 0, "total_us": 0.0,
                                         "min_us": float("inf"), "max_us": 0.0})
        d = ev.get("dur", 0.0)
        st["count"] += 1
        st["total_us"] += d
        st["min_us"] = min(st["min_us"], d)
        st["max_us"] = max(st["max_us"], d)
    lines = ["%-40s %8s %12s %12s %12s" % ("Name", "Calls", "Total(us)",
                                           "Min(us)", "Max(us)")]
    for name, st in sorted(agg.items(), key=lambda kv: -kv[1]["total_us"]):
        lines.append("%-40s %8d %12.1f %12.1f %12.1f"
                     % (name[:40], st["count"], st["total_us"],
                        st["min_us"], st["max_us"]))
    return "\n".join(lines)


def pause(profile_process="worker"):
    """Stop recording without clearing events (reference: profiler.py
    pause → MXProfilePause).  ``profile_process='server'`` forwards to
    the parameter-server processes like ``set_state`` does."""
    if profile_process == "server":
        return _server_command("pause", {})
    _state["running"] = False


def resume(profile_process="worker"):
    """Resume a paused recording; ``profile_process='server'`` forwards
    to the parameter-server processes like ``set_state`` does."""
    if profile_process == "server":
        return _server_command("resume", {})
    _state["running"] = True


# ------------------------------------------------------------- XLA traces


def start_xla_trace(log_dir="/tmp/mxnet_tpu_trace"):
    """Device-side trace via jax.profiler (TensorBoard/Perfetto viewable)."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _state["xla_dir"] = log_dir


def stop_xla_trace():
    import jax

    jax.profiler.stop_trace()
    return _state["xla_dir"]


# ------------------------------------------------------------- user scopes
# reference: c_api_profile.cc domains/tasks/frames/counters/markers


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = _now_us()

    def stop(self):
        if self._t0 is not None:
            add_event(self.name, self.domain.name, "X", ts=self._t0,
                      dur=_now_us() - self._t0)
            self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()


class Task(_Span):
    pass


class Frame(_Span):
    pass


class Event(_Span):
    def __init__(self, name):
        super().__init__(Domain("event"), name)


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self.value = value
        add_event(self.name, self.domain.name, "C",
                  args={self.name: value})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    __iadd__ = lambda self, d: (self.increment(d), self)[1]
    __isub__ = lambda self, d: (self.decrement(d), self)[1]


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        add_event(self.name, self.domain.name, "i",
                  args={"scope": scope})


# ------------------------------------------------------- env activation


def _dump_at_exit():
    if _state["running"] or _state["events"]:
        dump(finished=True)


def _activate_from_env():
    """``MXNET_TPU_PROFILE=<file>``: record the whole process and dump
    the chrome trace at exit — zero-code-change profiling of any
    training script (docs/OBSERVABILITY.md)."""
    fname = os.environ.get("MXNET_TPU_PROFILE")
    if not fname:
        return False
    import atexit

    # multi-rank runs launched WITHOUT tools/launch.py (which rewrites
    # the env per process) self-suffix the path — a non-zero rank must
    # not silently overwrite rank 0's trace
    set_config(filename=rank_suffix_path(fname), profile_all=True)
    set_state("run")
    atexit.register(_dump_at_exit)
    return True


_activate_from_env()
