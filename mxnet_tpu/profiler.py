"""Profiler — chrome://tracing output + aggregate stats.

Reference: src/profiler/profiler.h:256 (Profiler singleton, ProfileStat
arrays, chrome-tracing JSON dump :87,437), aggregate_stats.cc,
python/mxnet/profiler.py:33 (set_config/set_state/dump, custom
domains/tasks/counters/markers).

TPU-native: two layers. (1) A Python-side event recorder with the same
API (set_config/set_state/dump/dumps, Domain/Task/Frame/Counter/Marker)
producing chrome-tracing JSON — this traces the *framework* (op
dispatch, iterator, kvstore). (2) ``start_xla_trace``/``stop_xla_trace``
wrap ``jax.profiler`` for device-side traces viewable in TensorBoard /
Perfetto — the analog of the reference's device-level opr profiling,
since XLA owns kernel timing on TPU.
"""

from __future__ import annotations

import json
import os
import threading
import time

_state = {
    "config": {"profile_all": False, "profile_symbolic": True,
               "profile_imperative": True, "profile_memory": False,
               "profile_api": False, "aggregate_stats": False,
               "filename": "profile.json"},
    "running": False,
    "events": [],
    "lock": threading.Lock(),
    "xla_dir": None,
}


_kvstore_handle = None


def set_kvstore_handle(kv):
    """Register the kvstore used to reach parameter-server processes
    (reference: profiler.py set_kvstore_handle — enables
    profile_process='server')."""
    global _kvstore_handle
    _kvstore_handle = kv


def _server_command(fn, kwargs):
    import json as _json

    if _kvstore_handle is None:
        raise ValueError("profile_process='server' needs "
                         "profiler.set_kvstore_handle(kv) first")
    _kvstore_handle._send_command_to_servers(
        "profiler", _json.dumps({"fn": fn, "kwargs": kwargs}))


def set_config(**kwargs):
    """reference: profiler.py:33 set_config.  With
    profile_process='server' the config is forwarded to every
    parameter-server process (reference: KVStoreServerProfilerCommand,
    include/mxnet/kvstore.h:49)."""
    if kwargs.pop("profile_process", "worker") == "server":
        return _server_command("set_config", kwargs)
    _state["config"].update(kwargs)


def set_state(state="stop", profile_process="worker"):
    """'run' | 'stop' (reference: profiler.py:89)."""
    if profile_process == "server":
        return _server_command("set_state", {"state": state})
    if state == "run":
        _state["running"] = True
    elif state == "stop":
        _state["running"] = False
    else:
        raise ValueError("state must be 'run' or 'stop'")


def is_running():
    return _state["running"]


def _now_us():
    return time.perf_counter_ns() / 1000.0


def add_event(name, cat, ph, ts=None, pid=0, tid=None, args=None, dur=None):
    if not _state["running"]:
        return
    ev = {"name": name, "cat": cat, "ph": ph,
          "ts": ts if ts is not None else _now_us(),
          "pid": pid, "tid": tid if tid is not None else threading.get_ident()}
    if args:
        ev["args"] = args
    if dur is not None:
        ev["dur"] = dur
    with _state["lock"]:
        _state["events"].append(ev)


class scope:
    """``with profiler.scope('fwd'):`` records a complete event."""

    __slots__ = ("name", "cat", "args", "t0")

    def __init__(self, name, cat="framework", args=None):
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0 = _now_us()
        return self

    def __exit__(self, *a):
        add_event(self.name, self.cat, "X", ts=self.t0,
                  dur=_now_us() - self.t0, args=self.args)
        return False


class _NullSpan:
    """Shared do-nothing context manager: the disabled-profiler fast
    path of :func:`span` — no allocation, no timestamps."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_SPAN = _NullSpan()


def span(name, cat="framework", args=None):
    """Guard-first complete-event span for framework hot loops.

    Returns a shared no-op when the profiler is not recording, so
    instrumented code pays one flag check and no event/span allocation
    when telemetry is off (the hard constraint of PR 2's tentpole).
    Exceptions propagate; the event is still recorded."""
    if not _state["running"]:
        return _NULL_SPAN
    return scope(name, cat, args)


def counter(name, values, cat="framework"):
    """Guard-first chrome-trace counter ("C") event: one flag check and
    nothing else while the profiler is off.  ``values`` is the
    ``{series: number}`` args dict — the per-step telemetry sinks
    (device-memory timeline, numerics-health ``grad_norm`` /
    ``nan_total``) emit through this."""
    if not _state["running"]:
        return
    add_event(name, cat, "C", args=values)


def dump(finished=True, profile_process="worker"):
    """Write chrome-tracing JSON; returns the absolute path.

    ``finished=True`` also stops recording (reference semantics:
    profiler.py dump's `finished` finalizes the profiler)."""
    if profile_process == "server":
        return _server_command("dump", {"finished": finished})
    if finished:
        _state["running"] = False
    fname = _state["config"].get("filename", "profile.json")
    with _state["lock"]:
        events = list(_state["events"])
    with open(fname, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return os.path.abspath(fname)


def dumps(reset=False):
    """In-memory aggregate table (reference: aggregate_stats.cc)."""
    with _state["lock"]:
        events = list(_state["events"])
        if reset:
            _state["events"] = []
    agg = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        st = agg.setdefault(ev["name"], {"count": 0, "total_us": 0.0,
                                         "min_us": float("inf"), "max_us": 0.0})
        d = ev.get("dur", 0.0)
        st["count"] += 1
        st["total_us"] += d
        st["min_us"] = min(st["min_us"], d)
        st["max_us"] = max(st["max_us"], d)
    lines = ["%-40s %8s %12s %12s %12s" % ("Name", "Calls", "Total(us)",
                                           "Min(us)", "Max(us)")]
    for name, st in sorted(agg.items(), key=lambda kv: -kv[1]["total_us"]):
        lines.append("%-40s %8d %12.1f %12.1f %12.1f"
                     % (name[:40], st["count"], st["total_us"],
                        st["min_us"], st["max_us"]))
    return "\n".join(lines)


def pause(profile_process="worker"):
    """Stop recording without clearing events (reference: profiler.py
    pause → MXProfilePause).  ``profile_process='server'`` forwards to
    the parameter-server processes like ``set_state`` does."""
    if profile_process == "server":
        return _server_command("pause", {})
    _state["running"] = False


def resume(profile_process="worker"):
    """Resume a paused recording; ``profile_process='server'`` forwards
    to the parameter-server processes like ``set_state`` does."""
    if profile_process == "server":
        return _server_command("resume", {})
    _state["running"] = True


# ------------------------------------------------------------- XLA traces


def start_xla_trace(log_dir="/tmp/mxnet_tpu_trace"):
    """Device-side trace via jax.profiler (TensorBoard/Perfetto viewable)."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _state["xla_dir"] = log_dir


def stop_xla_trace():
    import jax

    jax.profiler.stop_trace()
    return _state["xla_dir"]


# ------------------------------------------------------------- user scopes
# reference: c_api_profile.cc domains/tasks/frames/counters/markers


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = _now_us()

    def stop(self):
        if self._t0 is not None:
            add_event(self.name, self.domain.name, "X", ts=self._t0,
                      dur=_now_us() - self._t0)
            self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()


class Task(_Span):
    pass


class Frame(_Span):
    pass


class Event(_Span):
    def __init__(self, name):
        super().__init__(Domain("event"), name)


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self.value = value
        add_event(self.name, self.domain.name, "C",
                  args={self.name: value})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    __iadd__ = lambda self, d: (self.increment(d), self)[1]
    __isub__ = lambda self, d: (self.decrement(d), self)[1]


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        add_event(self.name, self.domain.name, "i",
                  args={"scope": scope})


# ------------------------------------------------------- env activation


def _dump_at_exit():
    if _state["running"] or _state["events"]:
        dump(finished=True)


def _activate_from_env():
    """``MXNET_TPU_PROFILE=<file>``: record the whole process and dump
    the chrome trace at exit — zero-code-change profiling of any
    training script (docs/OBSERVABILITY.md)."""
    fname = os.environ.get("MXNET_TPU_PROFILE")
    if not fname:
        return False
    import atexit

    set_config(filename=fname, profile_all=True)
    set_state("run")
    atexit.register(_dump_at_exit)
    return True


_activate_from_env()
