"""mx.rtc — runtime kernel compilation.

Reference: python/mxnet/rtc.py (CudaModule over NVRTC,
include/mxnet/rtc.h:39).

TPU-native: the CUDA-source path cannot exist on TPU; the runtime
kernel facility here is **Pallas** — `PallasModule` compiles a Pallas
kernel function at runtime, the direct analog of CudaModule compiling
a CUDA C string.  CudaModule is kept as a clear error for API parity.
"""

from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["CudaModule", "PallasModule"]


class CudaModule:
    """Unavailable on TPU (reference: rtc.py CudaModule)."""

    def __init__(self, source, options=(), exports=()):
        raise MXNetError(
            "CudaModule (NVRTC) is not available on TPU. Use "
            "mx.rtc.PallasModule to JIT-compile a Pallas TPU kernel at "
            "runtime instead.")


class PallasModule:
    """Compile Pallas kernels at runtime — the TPU analog of NVRTC.

    kernel_fn: a function written with jax.experimental.pallas (pl.*)
    taking Refs; get_kernel returns a launcher with CudaModule-like
    call semantics.
    """

    def __init__(self, kernel_fn, out_shape_fn, grid=None):
        self._kernel_fn = kernel_fn
        self._out_shape_fn = out_shape_fn
        self._grid = grid

    def get_kernel(self, name=None, signature=None):
        import jax

        kernel_fn = self._kernel_fn
        out_shape_fn = self._out_shape_fn
        grid = self._grid

        class _Launcher:
            def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
                       shared_mem=0):
                from jax.experimental import pallas as pl

                arrays = [a.data_jax if isinstance(a, NDArray) else a
                          for a in args]
                out_shape = out_shape_fn(*arrays)
                kw = {}
                if grid_dims is not None or grid is not None:
                    # gridless kernels must OMIT the arg: pallas_call
                    # rejects an explicit grid=None
                    kw["grid"] = grid_dims if grid_dims is not None else grid
                if jax.default_backend() != "tpu":
                    # Mosaic compiles only on TPU; CPU (tests, local
                    # dev) runs the same kernel through the interpreter
                    kw["interpret"] = True
                fn = pl.pallas_call(kernel_fn, out_shape=out_shape, **kw)
                res = fn(*arrays)
                return NDArray(res)

            __call__ = launch

        return _Launcher()
