"""Test utilities (reference: python/mxnet/test_utils.py):
assert_almost_equal, check_numeric_gradient, check_symbolic_forward/
backward, check_consistency (eager-vs-jit-vs-sharded on TPU instead of
cpu-vs-gpu), rand_ndarray, default contexts.
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

_rng = _np.random.RandomState(1234)


def default_context():
    return current_context()


def set_default_context(ctx):
    Context._default_ctx.value = ctx


def default_dtype():
    return _np.float32


def rand_shape_2d(dim0=10, dim1=10):
    return (_rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(_rng.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_rng.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 distribution="uniform"):
    if distribution == "normal":
        data = _rng.standard_normal(shape)
    else:
        data = _rng.uniform(-1, 1, size=shape)
    arr = array(data.astype(dtype or _np.float32))
    if stype != "default":
        return arr.tostype(stype)
    return arr


def random_arrays(*shapes):
    arrays = [_rng.standard_normal(size=s).astype(_np.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def _as_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return _np.asarray(a)


def find_max_violation(a, b, rtol=None, atol=None):
    a, b = _as_np(a), _as_np(b)
    rtol = rtol or 1e-5
    atol = atol or 1e-20
    diff = _np.abs(a - b)
    tol = atol + rtol * _np.abs(b)
    violation = diff / (tol + 1e-37)
    idx = _np.unravel_index(_np.argmax(violation), violation.shape) \
        if violation.size else ()
    return idx, violation.max() if violation.size else 0.0


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """reference: test_utils.assert_almost_equal."""
    a_np, b_np = _as_np(a), _as_np(b)
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    if not _np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        idx, max_v = find_max_violation(a_np, b_np, rtol, atol)
        raise AssertionError(
            "Items are not equal (rtol=%g, atol=%g): max violation %.4g at %s\n"
            " %s: %s\n %s: %s" % (rtol, atol, max_v, idx, names[0],
                                  a_np.flat[:10], names[1], b_np.flat[:10]))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    return _np.allclose(_as_np(a), _as_np(b), rtol=rtol or 1e-5,
                        atol=atol or 1e-20, equal_nan=equal_nan)


def same(a, b):
    return _np.array_equal(_as_np(a), _as_np(b))


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None, dtype=_np.float32):
    """Compare symbolic gradients to central finite differences
    (reference: test_utils.check_numeric_gradient — the backbone of
    test_operator.py)."""
    ctx = ctx or current_context()
    if isinstance(location, (list, tuple)):
        arg_names = sym.list_arguments()
        location = dict(zip(arg_names, location))
    location = {k: (v if isinstance(v, NDArray) else array(v, dtype=dtype))
                for k, v in location.items()}
    # auto-fill parameter args the caller didn't supply (reference
    # behaviour: missing args get random values)
    missing = [n for n in sym.list_arguments() if n not in location]
    if missing:
        shapes, _, _ = sym.infer_shape_partial(
            **{k: v.shape for k, v in location.items()})
        by_name = dict(zip(sym.list_arguments(), shapes))
        rng = _np.random.RandomState(0)
        for n in missing:
            if by_name.get(n) is None:
                raise ValueError("cannot infer shape for %r; pass it in "
                                 "location" % n)
            location[n] = array(
                rng.uniform(-0.5, 0.5, by_name[n]).astype(dtype))
    if grad_nodes is None:
        grad_nodes = list(location.keys())

    ex = sym.bind(ctx, {k: v.copy() for k, v in location.items()},
                  args_grad={k: array(_np.zeros(v.shape, dtype=dtype))
                             for k, v in location.items() if k in grad_nodes},
                  grad_req={k: ("write" if k in grad_nodes else "null")
                            for k in location},
                  aux_states=aux_states)
    ex.forward(is_train=use_forward_train)
    out = ex.outputs[0]
    ograd = array(_np.ones(out.shape, dtype=dtype))
    ex.backward([ograd])
    sym_grads = {k: ex.grad_dict[k].asnumpy() for k in grad_nodes}

    def loss_at(loc):
        ex2 = sym.bind(ctx, {k: array(v) for k, v in loc.items()},
                       args_grad=None, grad_req={k: "null" for k in loc},
                       aux_states=aux_states)
        ex2.forward(is_train=use_forward_train)
        return ex2.outputs[0].asnumpy().sum()

    base = {k: v.asnumpy().astype(_np.float64) for k, v in location.items()}
    for name in grad_nodes:
        arr = base[name]
        num_grad = _np.zeros_like(arr)
        flat = arr.reshape(-1)
        ng_flat = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps / 2
            fp = loss_at(base)
            flat[i] = orig - numeric_eps / 2
            fm = loss_at(base)
            flat[i] = orig
            ng_flat[i] = (fp - fm) / numeric_eps
        assert_almost_equal(sym_grads[name], num_grad, rtol=rtol,
                            atol=atol or 1e-4,
                            names=("symbolic_grad(%s)" % name, "numeric_grad"))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None, equal_nan=False,
                           dtype=_np.float32):
    """reference: test_utils.check_symbolic_forward."""
    ctx = ctx or current_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    args = {k: (v if isinstance(v, NDArray) else array(v, dtype=dtype))
            for k, v in location.items()}
    ex = sym.bind(ctx, args, grad_req={k: "null" for k in args},
                  aux_states=aux_states)
    outputs = ex.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, rtol=rtol, atol=atol, equal_nan=equal_nan)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, dtype=_np.float32):
    """reference: test_utils.check_symbolic_backward."""
    ctx = ctx or current_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    args = {k: (v if isinstance(v, NDArray) else array(v, dtype=dtype))
            for k, v in location.items()}
    grads = {k: array(_np.zeros(v.shape, dtype=dtype)) for k, v in args.items()}
    ex = sym.bind(ctx, args, args_grad=grads, grad_req=grad_req,
                  aux_states=aux_states)
    ex.forward(is_train=True)
    ogs = [g if isinstance(g, NDArray) else array(g, dtype=dtype)
           for g in (out_grads if isinstance(out_grads, (list, tuple))
                     else [out_grads])]
    ex.backward(ogs)
    for name, exp in expected.items():
        assert_almost_equal(ex.grad_dict[name], exp, rtol=rtol, atol=atol,
                            names=("grad(%s)" % name, "expected"))
    return {k: v.asnumpy() for k, v in ex.grad_dict.items()}


def check_consistency(sym, ctx_list=None, scale=1.0, rtol=1e-4, atol=1e-4,
                      arg_params=None, aux_params=None, raise_on_err=True):
    """Cross-backend consistency: run the symbol (a) eagerly op-by-op via
    NDArray, (b) staged via the jitted Executor, (c) on every available
    device context — and compare.

    This is the TPU analog of the reference's cpu-vs-gpu
    check_consistency (tests/python/gpu/test_operator_gpu.py).
    """
    import jax

    if ctx_list is None:
        ctx_list = [{"ctx": cpu()}]
        if any(d.platform != "cpu" for d in jax.devices()):
            from .context import tpu

            ctx_list.append({"ctx": tpu()})
    arg_names = sym.list_arguments()
    shapes = {}
    for spec in ctx_list:
        for k, v in spec.items():
            if k != "ctx" and k != "type_dict":
                shapes[k] = v
    results = []
    base_args = None
    for spec in ctx_list:
        ctx = spec["ctx"]
        ex = sym.simple_bind(ctx=ctx, grad_req="null", **shapes)
        if base_args is None:
            base_args = {}
            for name in arg_names:
                arr = _rng.standard_normal(ex.arg_dict[name].shape) * scale
                base_args[name] = arr.astype(_np.float32)
        for name in arg_names:
            ex.arg_dict[name][:] = base_args[name]
        outs = ex.forward(is_train=False)
        results.append([o.asnumpy() for o in outs])
    ref = results[0]
    for res in results[1:]:
        for a, b in zip(ref, res):
            assert_almost_equal(a, b, rtol=rtol, atol=atol)
    return results


def check_op_consistency(op_name, arrays, attrs=None, rtol=1e-4, atol=1e-4,
                         shard_axis=0):
    """Run one op THREE ways and compare outputs:

    1. eager — the imperative NDArray dispatch (per-op jit cache);
    2. staged — a Symbol graph through the Executor (whole-graph jit);
    3. sharded — the pure fn jitted with its first input sharded over
       every available device (GSPMD partitions the computation).

    The TPU analog of the reference's cpu-vs-gpu ``check_consistency``
    (python/mxnet/test_utils.py): instead of two device backends, the
    three execution paths that must agree on this framework.
    Returns the eager outputs as numpy arrays.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from . import symbol as sym_mod
    from .ndarray import array
    from .ops import registry

    attrs = dict(attrs or {})
    op = registry.get(op_name)

    # 1. eager
    nd_in = [array(a) for a in arrays]
    from .ndarray.ndarray import imperative_invoke

    eager = [o.asnumpy() for o in imperative_invoke(op_name, nd_in, dict(attrs))]

    # 2. staged via symbol executor (aux inputs — e.g. BatchNorm moving
    # stats — bind as aux states, not arguments)
    variables = [sym_mod.Variable("in%d" % i) for i in range(len(arrays))]
    out_sym = getattr(sym_mod, op_name)(*variables, **attrs)
    by_name = {"in%d" % i: array(a) for i, a in enumerate(arrays)}
    args = {n: by_name[n] for n in out_sym.list_arguments()}
    aux = {n: by_name[n] for n in out_sym.list_auxiliary_states()}
    ex = out_sym.bind(cpu(), args, aux_states=aux)
    staged = [o.asnumpy() for o in ex.forward()]

    # 3. sharded over all devices (skipped when the axis doesn't divide)
    devices = jax.devices()
    n = len(devices)
    sharded = None
    if n > 1 and arrays and arrays[0].ndim > shard_axis and \
            arrays[0].shape[shard_axis] % n == 0:
        mesh = Mesh(_np.array(devices), ("dp",))
        spec = [None] * arrays[0].ndim
        spec[shard_axis] = "dp"
        shardings = [NamedSharding(mesh, PartitionSpec(*spec))] + \
            [NamedSharding(mesh, PartitionSpec())] * (len(arrays) - 1)
        fn = op.bind_attrs(op.canonicalize_attrs(attrs))
        jitted = jax.jit(fn, in_shardings=shardings)
        out = jitted(*[jnp.asarray(a) for a in arrays])
        out = out if isinstance(out, tuple) else (out,)
        sharded = [_np.asarray(o) for o in out]

    for name, res in (("staged", staged), ("sharded", sharded)):
        if res is None:
            continue
        assert len(res) == len(eager), (op_name, name)
        for a, b in zip(eager, res):
            assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                names=("eager", name))
    return eager


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    ex = sym.bind(ctx or current_context(),
                  {k: array(v) for k, v in inputs.items()})
    outputs = ex.forward(is_train=is_train)
    outputs = [o.asnumpy() for o in outputs]
    return outputs[0] if len(outputs) == 1 else outputs


class DummyIter:
    """Repeat one batch forever (reference: test_utils.DummyIter)."""

    def __init__(self, real_iter):
        self.real_iter = real_iter
        self.provide_data = real_iter.provide_data
        self.provide_label = real_iter.provide_label
        self.batch_size = real_iter.batch_size
        self.the_batch = next(real_iter)

    def __iter__(self):
        return self

    def next(self):
        return self.the_batch

    __next__ = next


def get_mnist():
    """MNIST arrays dict (reference: test_utils.get_mnist — downloads;
    here the zero-egress container serves MNISTIter's deterministic
    synthetic digits through the same contract)."""
    from .io.io import MNISTIter

    def _collect(which):
        it = MNISTIter(image=which, batch_size=100, shuffle=False)
        it.reset()
        data, label = [], []
        for b in it:
            data.append(b.data[0].asnumpy())
            label.append(b.label[0].asnumpy())
        return _np.concatenate(data), _np.concatenate(label)

    train_img, train_lbl = _collect("train")
    test_img, test_lbl = _collect("val")
    return {"train_data": train_img, "train_label": train_lbl,
            "test_data": test_img, "test_label": test_lbl}


def get_mnist_iterator(batch_size, input_shape, num_parts=1, part_index=0):
    """(train_iter, val_iter) pair (reference: get_mnist_iterator)."""
    from .io.io import MNISTIter

    flat = len(input_shape) == 1
    train = MNISTIter(image="train", batch_size=batch_size, shuffle=True,
                      flat=flat, num_parts=num_parts, part_index=part_index)
    val = MNISTIter(image="val", batch_size=batch_size, shuffle=False,
                    flat=flat)
    return train, val


def download(url, fname=None, dirname=None, overwrite=False, retries=5):
    """Reference-parity stub: this container has no network egress, so
    downloads must fail loudly instead of hanging (reference:
    test_utils.download fetches over HTTP)."""
    raise RuntimeError(
        "test_utils.download(%r): network egress is unavailable in this "
        "environment; stage files locally instead" % (url,))
