"""nd.contrib — control flow + contrib op namespace.

Reference: python/mxnet/ndarray/contrib.py (foreach, while_loop, cond)
over src/operator/control_flow.cc:1255,1316,1378.

TPU-native: instead of CachedOp subgraph nodes, the body is traced once
and lowered to lax.scan / lax.while_loop / lax.cond — the exact XLA
constructs the reference ops were designed to mirror (SURVEY.md §2.1
'Control-flow ops': "maps directly to XLA scan/while/cond").  Eager
semantics are preserved: inputs/outputs are NDArrays.
"""

from __future__ import annotations

from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _wrap(v, ctx):
    return NDArray(v, ctx)


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def _tree_unwrap(xs):
    if isinstance(xs, (list, tuple)):
        return [_tree_unwrap(x) for x in xs]
    return _unwrap(xs)


def _tree_wrap(vs, ctx):
    if isinstance(vs, (list, tuple)):
        return [_tree_wrap(v, ctx) for v in vs]
    return _wrap(vs, ctx)


def foreach(body, data, init_states):
    """Run `body(data_i, states) -> (out, new_states)` over axis 0 of
    data, stacking outputs (reference: contrib.foreach / _foreach op).

    Lowers to one lax.scan — the whole loop compiles to a single XLA
    While with the body fused.  Under autograd.record() it runs as an
    eager Python loop instead, so every op (including uses of
    closed-over Parameters) lands on the tape — exactly the
    reference's imperative foreach (python/mxnet/ndarray/contrib.py),
    whose eager path is a plain for loop.
    """
    import jax
    from jax import lax

    from .. import autograd as _ag

    single_data = isinstance(data, NDArray)
    ctx = (data if single_data else data[0])._ctx

    if _ag.is_recording():
        from . import stack as _stack

        def tree_slice(d, i):
            if isinstance(d, (list, tuple)):
                return [tree_slice(v, i) for v in d]
            return d[i]

        def tree_stack(rows_):
            if isinstance(rows_[0], (list, tuple)):
                return [tree_stack([r[k] for r in rows_])
                        for k in range(len(rows_[0]))]
            return _stack(*rows_, axis=0)

        def first_leaf(d):
            while isinstance(d, (list, tuple)):
                d = d[0]
            return d

        n = first_leaf(data).shape[0]
        states = init_states
        rows = []
        for i in range(n):
            out, states = body(tree_slice(data, i), states)
            rows.append(out)
        if not rows:
            return [], states
        return tree_stack(rows), states

    xs = _tree_unwrap(data)
    init = _tree_unwrap(init_states)

    def scan_body(carry, x):
        states_nd = _tree_wrap(carry, ctx)
        x_nd = _tree_wrap(x, ctx)
        out, new_states = body(x_nd, states_nd)
        return _tree_unwrap(new_states), _tree_unwrap(out)

    carry, ys = lax.scan(scan_body, init, xs)
    outs = _tree_wrap(ys, ctx)
    states = _tree_wrap(carry, ctx)
    return outs, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """reference: contrib.while_loop / _while_loop op.

    cond(*loop_vars) -> boolean scalar; func(*loop_vars) ->
    (step_output, new_loop_vars).  Per the reference, outputs are
    stacked into a max_iterations-capacity buffer (rows past the actual
    iteration count are undefined in the reference; zeros here).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    if max_iterations is None:
        raise ValueError("max_iterations is required (static bound for XLA)")
    max_iterations = int(max_iterations)
    ctx = loop_vars[0]._ctx
    init = [_unwrap(v) for v in loop_vars]

    if not any(isinstance(v, jax.core.Tracer) for v in init):
        # eager semantics (reference python/mxnet/ndarray/contrib.py
        # while_loop): a plain Python loop — func runs only while cond
        # holds; if cond is never satisfied, outputs are empty (the
        # reference documents exactly this asymmetry vs symbolic mode)
        from .. import autograd as _ag

        recording = _ag.is_recording()
        vars_ = list(loop_vars)
        rows = []
        steps = 0
        while steps < max_iterations and bool(np.asarray(_unwrap(cond(*vars_)))):
            out, new_vars = func(*vars_)
            # func may carry no per-step outputs (the reference accepts
            # an empty list; None is the natural Python spelling)
            out = ([] if out is None
                   else out if isinstance(out, (list, tuple)) else [out])
            # keep NDArray rows when recording so the stacked outputs
            # stay on the tape; raw values otherwise
            rows.append(list(out) if recording
                        else [_unwrap(o) for o in out])
            new_vars = new_vars if isinstance(new_vars, (list, tuple)) else [new_vars]
            vars_ = [v if isinstance(v, NDArray) else _wrap(v, ctx)
                     for v in new_vars]
            steps += 1
        outs = []
        if rows and recording:
            from . import stack as _stack
            from . import zeros as _zeros

            for k in range(len(rows[0])):
                row_k = [r[k] if isinstance(r[k], NDArray)
                         else _wrap(r[k], ctx) for r in rows]
                pad = [_zeros(tuple(row_k[0].shape), ctx=ctx,
                              dtype=row_k[0].dtype)
                       for _ in range(max_iterations - steps)]
                outs.append(_stack(*(row_k + pad), axis=0))
        elif rows:
            for k in range(len(rows[0])):
                buf = jnp.zeros((max_iterations,) + tuple(rows[0][k].shape),
                                rows[0][k].dtype)
                for i, row in enumerate(rows):
                    buf = buf.at[i].set(row[k])
                outs.append(_wrap(buf, ctx))
        return outs, list(vars_)

    # traced: output structure via abstract evaluation — func is never
    # executed on real data (shapes only), then one lax.while_loop
    def _probe(*vs):
        out, _ = func(*_tree_wrap(list(vs), ctx))
        out = ([] if out is None
               else out if isinstance(out, (list, tuple)) else [out])
        return [_unwrap(o) for o in out]

    probe_out = jax.eval_shape(_probe, *init)

    bufs = [jnp.zeros((max_iterations,) + tuple(o.shape),
                      dtype=o.dtype) for o in probe_out]

    def cond_fn(state):
        i, vars_, _ = state
        c = cond(*_tree_wrap(list(vars_), ctx))
        return jnp.logical_and(i < max_iterations,
                               _unwrap(c).astype(bool).reshape(()))

    def body_fn(state):
        i, vars_, bufs_ = state
        out, new_vars = func(*_tree_wrap(list(vars_), ctx))
        out = ([] if out is None
               else out if isinstance(out, (list, tuple)) else [out])
        new_bufs = tuple(b.at[i].set(_unwrap(o)) for b, o in zip(bufs_, out))
        return (i + 1, tuple(_unwrap(v) for v in new_vars), new_bufs)

    i, final_vars, final_bufs = lax.while_loop(
        cond_fn, body_fn, (jnp.asarray(0), tuple(init), tuple(bufs)))
    outs = [_wrap(b, ctx) for b in final_bufs]
    return outs, [_wrap(v, ctx) for v in final_vars]


def cond(pred, then_func, else_func):
    """reference: contrib.cond / _cond op → lax.cond.

    Eager (concrete pred): only the selected branch runs, matching the
    reference's imperative semantics.  Traced: lax.cond.
    """
    import jax
    import numpy as np
    from jax import lax

    p = _unwrap(pred)
    ctx = pred._ctx if isinstance(pred, NDArray) else None

    if not isinstance(p, jax.core.Tracer):
        return then_func() if bool(np.asarray(p)) else else_func()

    def t(_):
        return _tree_unwrap(then_func())

    def e(_):
        return _tree_unwrap(else_func())

    res = lax.cond(p.astype(bool).reshape(()), t, e, None)
    return _tree_wrap(res, ctx)


def _install_contrib_ops(namespace):
    """Expose contrib-registered ops as nd.contrib.* (reference: the
    _contrib_ C++ prefix populating ndarray/contrib.py)."""
    from ..ops import registry as _reg
    from . import register as _register

    names = [n for n in _reg.list_ops()
             if n in ("box_nms", "box_iou", "MultiBoxPrior", "MultiBoxTarget",
                      "MultiBoxDetection", "ROIAlign", "_contrib_Proposal",
                      "_contrib_PSROIPooling",
                      "_contrib_DeformableConvolution",
                      "BilinearResize2D",
                      "AdaptiveAvgPooling2D", "boolean_mask", "quadratic",
                      "arange_like", "getnnz", "index_copy", "index_add",
                      "adamw_update", "_contrib_flash_attention",
                      "_contrib_div_sqrt_dim",
                      "_contrib_interleaved_matmul_selfatt_qk",
                      "_contrib_interleaved_matmul_selfatt_valatt")]
    _register.populate(namespace, names)
    return namespace


_install_contrib_ops(globals())
