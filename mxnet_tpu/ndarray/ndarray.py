"""NDArray — the imperative tensor, backed by a jax.Array in HBM.

Reference: include/mxnet/ndarray.h:82 (class NDArray), src/ndarray/
ndarray.cc, python/mxnet/ndarray/ndarray.py.

TPU-native design notes:

- The reference NDArray is a ref-counted Chunk(Storage::Handle + engine
  var); ops are pushed to the async engine and the user thread never
  blocks until an explicit sync (``asnumpy``/``wait_to_read``).  Here the
  buffer is a ``jax.Array`` — XLA's async dispatch *is* the engine:
  every op returns immediately with a future-backed array, and
  ``asnumpy()``/``wait_to_read()`` are the sync points
  (``jax.Array.block_until_ready``).  No re-implementation of
  ThreadedEngine is needed or wanted (SURVEY.md §7 design stance).
- NDArray is *mutable* at the Python level (``a[:] = x``, ``a += b``,
  optimizer in-place updates): mutation rebinds the internal ``_data``
  to a new functional value (``jax.Array.at[...]``), which XLA turns
  into in-place donation where safe.  Basic-slice reads return a view
  object carrying a writeback link to the base (parity with the
  reference's Slice/At write-through views, ndarray.h:810).
- Eager ops dispatch through the op registry's per-op jit cache
  (ops/registry.py), so steady-state imperative code runs compiled
  kernels; ``hybridize``/Symbol stage whole graphs instead.
"""

from __future__ import annotations

import numpy as _np

from .. import device_memory as _dm
from .. import profiler as _prof
from .. import runtime_stats as _rts
from ..base import MXNetError, np_dtype, numeric_types
from ..context import Context, current_context
from ..ops import registry as _reg

# dict read on every dispatch: cheapest possible "is the profiler on"
# check (guard-first — no event/span allocation when it is off)
_prof_state = _prof._state
# same guard shape for the device-buffer tracker (device_memory.py)
_dm_state = _dm._state

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concatenate", "save", "load", "waitall", "imperative_invoke",
           "moveaxis", "stack_arrays"]

# ops that consume an explicit PRNG key as first tensor input
RANDOM_OPS = {
    "_random_uniform", "_random_normal", "_random_gamma", "_random_exponential",
    "_random_poisson", "_random_negative_binomial",
    "_random_generalized_negative_binomial", "_random_randint",
    "_sample_multinomial", "_sample_uniform", "_sample_normal", "_sample_gamma",
    "_sample_exponential", "_sample_poisson", "_sample_negative_binomial",
    "_sample_generalized_negative_binomial",
    "_shuffle", "_sample_unique_zipfian", "RNN",
}


def _jnp():
    import jax.numpy as jnp

    return jnp


class NDArray:
    """An n-dimensional array on a device (TPU HBM by default)."""

    __slots__ = ("_data", "_ctx", "_ag_node", "_writeback", "__weakref__")

    # make numpy defer to NDArray in mixed expressions (np * nd)
    __array_priority__ = 1000.0

    def __init__(self, data, ctx=None, _writeback=None):
        self._data = data
        self._ctx = ctx
        self._ag_node = None
        self._writeback = _writeback  # (base NDArray, index) for slice views
        if _dm_state["on"]:
            _dm.track(data)

    # ------------------------------------------------------------- basics
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        try:
            dev = list(self._data.devices())[0]
            platform = dev.platform
        except Exception:
            return current_context()
        if platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    ctx = context

    @property
    def data_jax(self):
        """The underlying jax.Array (TPU-native escape hatch)."""
        return self._data

    def __repr__(self):
        try:
            arr = self.asnumpy()
            body = str(arr)
        except Exception as e:  # async error surfaces at sync point
            body = "<error: %s>" % e
        return "%s\n<NDArray %s @%s>" % (body, "x".join(map(str, self.shape)), self.context)

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asnumpy().item())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------- sync
    def asnumpy(self):
        """Copy to host, blocking until the value is ready.

        Reference parity: the implicit engine sync point
        (``NDArray::WaitToRead`` + copy, ndarray.h:359).
        """
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().item()

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        self._data.block_until_ready()

    def wait_to_write(self):
        self._data.block_until_ready()

    # ------------------------------------------------------------- dtype/device
    def astype(self, dtype, copy=True):
        d = np_dtype(dtype)
        if not copy and self.dtype == d:
            return self
        return NDArray(self._data.astype(d), self._ctx)

    def as_in_context(self, ctx):
        import jax

        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device), ctx)

    as_in_ctx = as_in_context

    def copyto(self, other):
        """Copy into another NDArray/Context (reference: CopyFromTo,
        src/ndarray/ndarray.cc:1186)."""
        import jax

        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device), other)
        if not isinstance(other, NDArray):
            raise TypeError("copyto target must be NDArray or Context")
        if other.shape != self.shape:
            raise ValueError("copyto shape mismatch %s vs %s" % (self.shape, other.shape))
        other._assign(jax.device_put(self._data.astype(other.dtype),
                                     other.context.jax_device))
        return other

    def copy(self):
        return NDArray(self._data + 0 if self.dtype != _np.bool_ else self._data,
                       self._ctx)

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp

        return _sp.cast_storage(self, stype)

    @property
    def stype(self):
        return "default"

    # ------------------------------------------------------------- mutation
    def _assign(self, new_jax_value):
        """Rebind the buffer; propagate through view writeback if present."""
        from .. import autograd as _ag

        if self._ag_node is not None and _ag.is_recording():
            raise MXNetError(
                "in-place write on an array participating in a recorded graph"
            )
        if _dm_state["on"]:
            _dm.track(new_jax_value, "_assign")
        self._data = new_jax_value
        if self._writeback is not None:
            base, index = self._writeback
            if base._needs_i64():
                import jax

                with jax.enable_x64():
                    base._assign(base._data.at[index].set(new_jax_value))
            else:
                base._assign(base._data.at[index].set(new_jax_value))

    def __setitem__(self, key, value):
        jnp = _jnp()
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, numeric_types):
            v = value
        else:
            v = jnp.asarray(value)
        if key is None or key == slice(None):
            if isinstance(v, (int, float)):
                self._assign(jnp.full(self.shape, v, dtype=self.dtype))
            else:
                v = jnp.asarray(v, dtype=self.dtype)
                self._assign(jnp.broadcast_to(v, self.shape) + 0)
            return
        if self._needs_i64():
            import jax

            key = _clean_index(key, _np.int64)
            with jax.enable_x64():
                self._assign(self._data.at[key].set(v))
            return
        key = _clean_index(key)
        self._assign(self._data.at[key].set(v))

    def _needs_i64(self):
        """Arrays beyond int32 addressing need 64-bit gather/scatter
        indices (reference: INT64_TENSOR_SIZE builds; nightly
        test_large_array.py).  Host/CPU-backed arrays only: XLA's TPU
        backend has no 64-bit scatter, and a single chip's HBM cannot
        hold such a tensor anyway — on device, exceeding int32 addressing
        means sharding over a mesh."""
        return any(d > 2**31 - 1 for d in self._data.shape)

    def _on_tape(self):
        """Whether gradients can flow through this array: it was
        attach_grad()ed or produced by a recorded op."""
        return self._ag_node is not None

    def __getitem__(self, key):
        from .. import autograd as _ag

        record = _ag.is_recording() and self._on_tape()
        if key is None:
            if record:
                from ..ops.matrix import encode_basic_index

                return imperative_invoke(
                    "_basic_index", [self],
                    {"key": encode_basic_index((None,))})[0]
            return NDArray(self._data[None], self._ctx)
        if self._needs_i64():
            import jax

            ck = _clean_index(key, _np.int64)
            if _is_basic_index(ck):
                if record:
                    from ..ops.matrix import encode_basic_index

                    return imperative_invoke(
                        "_basic_index", [self],
                        {"key": encode_basic_index(ck)})[0]
                with jax.enable_x64():
                    out = self._data[ck]
                if isinstance(ck, tuple) and any(k is None for k in ck):
                    return NDArray(out, self._ctx)  # no scatter target
                # keep the reference's Slice/At write-through views on
                # the int64 path too (same program, same semantics,
                # regardless of array size)
                return NDArray(out, self._ctx, _writeback=(self, ck))
            if record:
                raise MXNetError(
                    "advanced indexing of an int64-addressed array is "
                    "not differentiable; read it outside "
                    "autograd.record() or via .detach()")
            with jax.enable_x64():
                return NDArray(self._data[ck], self._ctx)
        ck = _clean_index(key)
        if _is_basic_index(ck):
            if record:
                # an on-tape read through a view would fall off the tape
                # — route through the registered _basic_index op so it
                # joins the autograd graph (reference: record-able
                # Slice/At views, src/ndarray/ndarray.cc:234,267)
                from ..ops.matrix import encode_basic_index

                return imperative_invoke(
                    "_basic_index", [self],
                    {"key": encode_basic_index(ck)})[0]
            if isinstance(ck, tuple) and any(k is None for k in ck):
                # newaxis views have no scatter target — plain copy
                return NDArray(self._data[ck], self._ctx)
            # basic index → view with writeback (reference Slice/At views)
            return NDArray(self._data[ck], self._ctx, _writeback=(self, ck))
        if isinstance(ck, NDArray):
            ck = ck._data.astype("int32")
        if record:
            if not isinstance(ck, tuple) \
                    and getattr(ck, "ndim", None) is not None:
                # single integer-array index of an on-tape array = a row
                # gather; route through `take` so it joins the tape.
                # `take` clamps, so resolve negative indices first
                jnp = _jnp()
                arr = ck if hasattr(ck, "devices") else jnp.asarray(ck)
                arr = jnp.where(arr < 0, arr + self._data.shape[0], arr)
                return imperative_invoke("take", [self, NDArray(arr,
                                                                self._ctx)],
                                         {"axis": 0, "mode": "clip"})[0]
            raise MXNetError(
                "advanced indexing with %r is not differentiable here; "
                "read it outside autograd.record() / via .detach(), or "
                "use take/gather_nd ops" % (key,))
        return NDArray(self._data[ck], self._ctx)

    def slice(self, begin, end, step=None):
        return imperative_invoke("slice", [self], {"begin": begin, "end": end,
                                                   "step": step or ()})[0]

    def slice_axis(self, axis, begin, end):
        return imperative_invoke("slice_axis", [self],
                                 {"axis": axis, "begin": begin, "end": end})[0]

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate gradient buffer and mark for autograd
        (reference: python/mxnet/ndarray/ndarray.py attach_grad →
        MXAutogradMarkVariables)."""
        from .. import autograd as _ag

        _ag.mark_variables([self], [zeros(self.shape, dtype=self.dtype,
                                          ctx=self.context)], grad_req)

    @property
    def grad(self):
        from .. import autograd as _ag

        return _ag.get_grad(self)

    def detach(self):
        out = NDArray(self._data, self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd as _ag

        _ag.backward([self], [out_grad] if out_grad is not None else None,
                     retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------- ops sugar
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return imperative_invoke("Reshape", [self],
                                 {"shape": shape,
                                  "reverse": kwargs.get("reverse", False)})[0]

    def reshape_like(self, other):
        return imperative_invoke("reshape_like", [self, other], {})[0]

    def expand_dims(self, axis):
        return imperative_invoke("expand_dims", [self], {"axis": axis})[0]

    def squeeze(self, axis=None):
        return imperative_invoke("squeeze", [self], {"axis": axis})[0]

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return imperative_invoke("transpose", [self], {"axes": axes})[0]

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        return imperative_invoke("Flatten", [self], {})[0]

    def flip(self, axis):
        return imperative_invoke("reverse", [self], {"axis": axis})[0]

    def sum(self, axis=None, keepdims=False, dtype=None, **kw):
        return imperative_invoke("sum", [self], {"axis": axis, "keepdims": keepdims,
                                                 "dtype": dtype})[0]

    def mean(self, axis=None, keepdims=False, dtype=None, **kw):
        return imperative_invoke("mean", [self], {"axis": axis, "keepdims": keepdims,
                                                  "dtype": dtype})[0]

    def max(self, axis=None, keepdims=False):
        return imperative_invoke("max", [self], {"axis": axis, "keepdims": keepdims})[0]

    def min(self, axis=None, keepdims=False):
        return imperative_invoke("min", [self], {"axis": axis, "keepdims": keepdims})[0]

    def prod(self, axis=None, keepdims=False):
        return imperative_invoke("prod", [self], {"axis": axis, "keepdims": keepdims})[0]

    def argmax(self, axis=None, keepdims=False):
        return imperative_invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})[0]

    def pick(self, index, axis=-1, keepdims=False, mode="clip"):
        return imperative_invoke("pick", [self, index],
                                 {"axis": axis, "keepdims": keepdims,
                                  "mode": mode})[0]

    def argmin(self, axis=None, keepdims=False):
        return imperative_invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})[0]

    def norm(self, ord=2, axis=None, keepdims=False):
        return imperative_invoke("norm", [self], {"ord": ord, "axis": axis,
                                                  "keepdims": keepdims})[0]

    def abs(self):
        return imperative_invoke("abs", [self], {})[0]

    def sqrt(self):
        return imperative_invoke("sqrt", [self], {})[0]

    def square(self):
        return imperative_invoke("square", [self], {})[0]

    def exp(self):
        return imperative_invoke("exp", [self], {})[0]

    def log(self):
        return imperative_invoke("log", [self], {})[0]

    def sigmoid(self):
        return imperative_invoke("sigmoid", [self], {})[0]

    def tanh(self):
        return imperative_invoke("tanh", [self], {})[0]

    def relu(self):
        return imperative_invoke("relu", [self], {})[0]

    def softmax(self, axis=-1):
        return imperative_invoke("softmax", [self], {"axis": axis})[0]

    def log_softmax(self, axis=-1):
        return imperative_invoke("log_softmax", [self], {"axis": axis})[0]

    def clip(self, a_min, a_max):
        return imperative_invoke("clip", [self], {"a_min": a_min, "a_max": a_max})[0]

    def round(self):
        return imperative_invoke("round", [self], {})[0]

    def sign(self):
        return imperative_invoke("sign", [self], {})[0]

    def sort(self, axis=-1, is_ascend=True):
        return imperative_invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})[0]

    def argsort(self, axis=-1, is_ascend=True):
        return imperative_invoke("argsort", [self], {"axis": axis,
                                                     "is_ascend": is_ascend})[0]

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        out = imperative_invoke("topk", [self], {"axis": axis, "k": k,
                                                 "ret_typ": ret_typ,
                                                 "is_ascend": is_ascend})
        return out if len(out) > 1 else out[0]

    def take(self, indices, axis=0, mode="clip"):
        return imperative_invoke("take", [self, _as_nd(indices)],
                                 {"axis": axis, "mode": mode})[0]

    def one_hot(self, depth, **kw):
        return imperative_invoke("one_hot", [self], {"depth": depth, **kw})[0]

    def broadcast_to(self, shape):
        return imperative_invoke("broadcast_to", [self], {"shape": shape})[0]

    def broadcast_like(self, other):
        return imperative_invoke("broadcast_like", [self, other], {})[0]

    def tile(self, reps):
        return imperative_invoke("tile", [self], {"reps": reps})[0]

    def repeat(self, repeats, axis=None):
        return imperative_invoke("repeat", [self], {"repeats": repeats, "axis": axis})[0]

    def pad(self, mode="constant", pad_width=(), constant_value=0.0):
        return imperative_invoke("Pad", [self], {"mode": mode, "pad_width": pad_width,
                                                 "constant_value": constant_value})[0]

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return imperative_invoke("SliceChannel", [self],
                                 {"num_outputs": num_outputs, "axis": axis,
                                  "squeeze_axis": squeeze_axis})

    def diag(self, k=0):
        return imperative_invoke("diag", [self], {"k": k})[0]

    def dot(self, other, transpose_a=False, transpose_b=False):
        return imperative_invoke("dot", [self, other],
                                 {"transpose_a": transpose_a,
                                  "transpose_b": transpose_b})[0]

    # ------------------------------------------------------------- arithmetic
    def _binop(self, other, opname, scalarname, reverse=False):
        if isinstance(other, NDArray):
            args = [other, self] if reverse else [self, other]
            return imperative_invoke(opname, args, {})[0]
        if isinstance(other, numeric_types):
            sname = scalarname
            if reverse and "_r" + scalarname[1:] in _SCALAR_REV:
                sname = "_r" + scalarname[1:]
            return imperative_invoke(sname, [self], {"scalar": float(other)})[0]
        return self._binop(array(other, ctx=self.context), opname, scalarname, reverse)

    def __add__(self, o):
        return self._binop(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binop(o, "broadcast_sub", "_minus_scalar", reverse=True)

    def __matmul__(self, o):
        """numpy @ semantics: 2-D dot, batched matmul for higher ranks
        (mx dot is a tensordot over last/first axes — different contract)."""
        import jax.numpy as jnp

        other = o._data if isinstance(o, NDArray) else jnp.asarray(o)
        return NDArray(jnp.matmul(self._data, other), self._ctx)

    def __rmatmul__(self, o):
        import jax.numpy as jnp

        other = o._data if isinstance(o, NDArray) else jnp.asarray(o)
        return NDArray(jnp.matmul(other, self._data), self._ctx)

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binop(o, "broadcast_div", "_div_scalar", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        return self._binop(o, "broadcast_mod", "_mod_scalar", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar", reverse=True)

    def __neg__(self):
        return imperative_invoke("negative", [self], {})[0]

    def __abs__(self):
        return self.abs()

    def __eq__(self, o):
        if o is None:
            return False
        return self._binop(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__

    def __iadd__(self, o):
        out = self.__add__(o)
        self._assign(out._data)
        return self

    def __isub__(self, o):
        out = self.__sub__(o)
        self._assign(out._data)
        return self

    def __imul__(self, o):
        out = self.__mul__(o)
        self._assign(out._data)
        return self

    def __itruediv__(self, o):
        out = self.__truediv__(o)
        self._assign(out._data)
        return self

    # numpy interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def to_dlpack_for_read(self):
        return self._data.__dlpack__()

    to_dlpack_for_write = to_dlpack_for_read


_SCALAR_REV = {"_rminus_scalar", "_rdiv_scalar", "_rmod_scalar", "_rpower_scalar"}


def _clean_index(key, idx_dtype=_np.int32):
    """Convert NDArray indices inside a key to jax/numpy arrays.

    idx_dtype: int64 for arrays addressed beyond int32 (INT64_TENSOR_SIZE
    paths) — truncating here would silently wrap large indices."""
    if isinstance(key, NDArray):
        return key._data.astype(idx_dtype)
    if isinstance(key, tuple):
        return tuple(
            k._data.astype(idx_dtype) if isinstance(k, NDArray) else k
            for k in key
        )
    if isinstance(key, (list, _np.ndarray)):
        return _np.asarray(key, dtype=idx_dtype)
    return key


def _is_basic_index(key):
    if isinstance(key, (int, slice)) or key is Ellipsis:
        return True
    if isinstance(key, tuple):
        return all(isinstance(k, (int, slice)) or k is Ellipsis or k is None
                   for k in key)
    return False


def _as_nd(x, ctx=None):
    if isinstance(x, NDArray):
        return x
    return array(x, ctx=ctx)


# ----------------------------------------------------------------- dispatch


import contextlib


@contextlib.contextmanager
def _op_errors(op_name, arrays):
    """Surface op failures as MXNetError (reference: every imperative
    error crosses the C API as MXNetError, src/c_api/c_api_error.cc).
    Under jit tracing the original jax error types are kept — hybrid
    callers and jax itself dispatch on them."""
    try:
        yield
    except (TypeError, ValueError) as e:
        if isinstance(e, ValueError) and "incompatible devices" in str(e):
            raise  # handled by the cross-device retry in the caller
        import jax

        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            raise
        raise MXNetError("%s: %s" % (op_name, e)) from e


def imperative_invoke(op_name, inputs, attrs, out=None):
    """The imperative dispatch path.

    Reference analog: MXImperativeInvokeEx → Imperative::Invoke
    (src/c_api/c_api_ndarray.cc:132, src/imperative/imperative.cc) —
    shape/type inference, engine push, and autograd recording in one.
    Here: unwrap → (jit-cached) pure fn → wrap, with jax.vjp capture when
    autograd is recording.
    """
    from .. import autograd as _ag

    op = _reg.get(op_name)
    attrs = {k: v for k, v in attrs.items() if v is not None}
    attrs = op.canonicalize_attrs(attrs)

    arrays = [a._data if isinstance(a, NDArray) else a for a in inputs]
    ctx = None
    for a in inputs:
        if isinstance(a, NDArray):
            ctx = a._ctx
            break

    needs_key = op_name in RANDOM_OPS
    if op_name == "RNN" and not _ag.is_training():
        # inference pass disables inter-layer dropout (reference: cuDNN RNN
        # forward-inference path, src/operator/cudnn_rnn-inl.h)
        attrs = dict(attrs, p=0.0)
    if op_name == "IdentityAttachKLSparseReg":
        # the aux moving-average updates only in the training pass
        # (reference updates it in Backward,
        # identity_attach_KL_sparse_reg-inl.h).  Resolved HERE so the
        # flag lands in the jit cache key — a Python branch inside the
        # op fn would be baked in by whichever mode compiled first.
        attrs = dict(attrs, _train=_ag.is_training())
    if op_name == "Dropout":
        # training-mode gate (reference: dropout.cc runs only in train pass)
        if attrs.get("mode", "training") == "always" or _ag.is_training():
            needs_key = True  # key=... kwarg threaded below
        else:
            return _wrap_outputs((arrays[0],), ctx, out, op=op.name)

    if needs_key:
        from ..random import next_key

        arrays = [next_key()] + arrays

    recording = _ag.is_recording() and _ag._any_recorded(inputs)
    if recording:
        import jax

        fn = op.bind_attrs(attrs)
        # telemetry is keyed on op.name so aliases (nd.identity vs
        # '_copy') aggregate into ONE per-op row, matching jitted_ex.
        # vjp capture bypasses the static jit cache by design — the
        # span still shows where forward-trace time goes in training
        _rts.record_dispatch(op.name, "uncached")
        with _prof.span("dispatch:" + op.name, "operator",
                        args={"op": op.name, "cache": "bypass-autograd"}
                        if _prof_state["running"] else None):
            with _op_errors(op_name, arrays):
                if needs_key:
                    outv, vjp_fn = _vjp_with_aux(fn, arrays)
                else:
                    outv, vjp_fn = jax.vjp(fn, *arrays)
        result = outv if isinstance(outv, tuple) else (outv,)
        out_nds = _wrap_outputs(result, ctx, out, op=op.name)
        _ag.record_op(inputs, out_nds, vjp_fn, op_name=op_name, attrs=attrs)
        return out_nds

    if needs_key:
        # keys vary per call → bypass the static jit cache (jax still
        # compiles the underlying primitives)
        _rts.record_dispatch(op.name, "uncached")
        with _prof.span("dispatch:" + op.name, "operator",
                        args={"op": op.name, "cache": "bypass-rng"}
                        if _prof_state["running"] else None):
            with _op_errors(op_name, arrays):
                result = op.bind_attrs(attrs)(*arrays)
    else:
        result = _dispatch_jit(op, op_name, attrs, arrays)
    result = result if isinstance(result, tuple) else (result,)
    return _wrap_outputs(result, ctx, out, op=op.name)


def _dispatch_jit(op, op_name, attrs, arrays):
    """The jit-cached dispatch path, instrumented.

    Always (profiler on or off): the registry counts the cache hit/miss
    and storms (inside ``jitted_ex``), and a miss's wall-time — which
    the trace+XLA-compile dominates, execution being async-dispatched —
    is attributed to ``runtime_stats`` compile_seconds.  Guard-first:
    when the profiler is off and the cache hits, the extra cost is one
    flag read — no timestamps, no event allocation, no host sync."""
    entry, hit = op.jitted_ex(attrs)
    cname = op.name  # canonical — jitted_ex counts under this name
    prof_on = _prof_state["running"]
    if hit and not prof_on and not _rts.DIAG_TIMING:
        return _call_jit_entry(op_name, cname, entry, arrays)
    t0 = _prof._now_us()
    result = _call_jit_entry(op_name, cname, entry, arrays)
    dur = _prof._now_us() - t0
    if not hit:
        _rts.add_compile_seconds(cname, dur / 1e6)
        # compile-time-only XLA cost/memory analysis of the fresh
        # entry (flops, bytes accessed, output/temp footprint) — feeds
        # the runtime_stats roofline/footprint sections.  Never on the
        # hit path; no-op unless cost capture is active (registry).
        op.analyze_entry(attrs, arrays)
    else:
        # timed CACHE-WARM wall-time per op (profiler on, or a
        # MXNET_TPU_DIAG run — the dump needs rate denominators): the
        # achieved GB/s / GFLOP/s divisor.  Misses are excluded —
        # their dur is compile-dominated and already attributed to
        # compile_seconds; folding it in would put every freshly
        # compiled op at the top of the roofline table
        _rts.add_dispatch_seconds(cname, dur / 1e6)
    if prof_on:
        # aval churn recompiles inside the jax.jit entry (registry-level
        # hit!) — feed shape/dtype signatures to the storm detector
        _rts.note_aval_key(cname, tuple(
            (tuple(getattr(a, "shape", ())), str(getattr(a, "dtype", "")))
            for a in arrays))
        ev_args = {"op": cname, "cache": "hit" if hit else "miss"}
        if not hit:
            ev_args["compile_ms"] = round(dur / 1e3, 3)
        _prof.add_event("dispatch:" + cname, "operator", "X", ts=t0,
                        dur=dur, args=ev_args)
    return result


def _call_jit_entry(op_name, cname, entry, arrays):
    try:
        with _op_errors(op_name, arrays):
            return entry(*arrays)
    except ValueError as e:
        if "incompatible devices" not in str(e):
            raise
        # cross-device inputs (e.g. kvstore reduce over per-device
        # grads): gather to the first input's device, like the
        # reference's CommCPU copy-to-reduce (src/kvstore/comm.h:103)
        import jax

        _rts.record_fallback(cname, "cross-device")
        dev = list(arrays[0].devices())[0]
        arrays = [jax.device_put(a, dev) for a in arrays]
        with _op_errors(op_name, arrays):
            return entry(*arrays)


def _vjp_with_aux(fn, arrays):
    """vjp over (key, *tensors): drop the key cotangent."""
    import jax

    outv, vjp_all = jax.vjp(fn, *arrays)

    def vjp_fn(ct):
        grads = vjp_all(ct)
        return grads[1:]  # drop key cotangent

    return outv, vjp_fn


def _wrap_outputs(result, ctx, out=None, op=None):
    if _dm_state["on"]:
        # label output buffers with the creating op for the per-op
        # memory breakdown; restore so unrelated wraps don't inherit it
        prev = _dm.set_origin(op)
        try:
            nds = [NDArray(r, ctx) for r in result]
        finally:
            _dm.set_origin(prev)
    else:
        nds = [NDArray(r, ctx) for r in result]
    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs, nds):
            dst._assign(src._data)
        return list(outs)
    return nds


# ----------------------------------------------------------------- creation


def array(source, ctx=None, dtype=None):
    import jax

    if isinstance(source, (NDArray, jax.Array)):
        # device-backed sources stay on device: a host roundtrip here
        # (asnumpy + re-upload) would block eager dispatch — this is
        # the hot path for `nd +/* raw-jax-array` arithmetic (mxlint:
        # trace-host-sync caught the old copy).  Typed sources keep
        # their dtype (f64 narrows: fp32-native framework).
        src = source._data if isinstance(source, NDArray) else source
        if dtype is not None:
            d = np_dtype(dtype)
        elif src.dtype == _np.float64:
            d = _np.float32  # framework is fp32-native
        else:
            d = src.dtype
        ctx = ctx or current_context()
        dev = ctx.jax_device  # outside the try: a bad ctx must raise
        try:
            same_device = dev in src.devices()
        except Exception:  # tracer / abstract value: no device yet
            same_device = None
        if src.dtype != d:
            src = src.astype(d)  # fresh buffer, already a snapshot
        elif same_device:
            # nd.array is documented as a snapshot — a same-device
            # device_put would alias the source buffer, and a later
            # donated jit step (parallel/gluon_step.py) would delete
            # it out from under the snapshot.  The cross-device
            # transfer below already yields an independent buffer.
            src = _jnp().array(src, copy=True)
        if same_device is False:
            src = jax.device_put(src, dev)
        if _dm_state["on"]:
            _dm.track(src, "array")
        return NDArray(src, ctx)
    src = _np.asarray(source)
    if dtype is None:
        if isinstance(source, _np.ndarray):
            # typed sources keep their dtype (float64 narrows: the
            # framework is fp32-native, reference does the same)
            dtype = src.dtype if src.dtype != _np.float64 else _np.float32
        else:
            # python lists/scalars default to float32 — the reference's
            # documented nd.array semantics (python/mxnet/ndarray/
            # utils.py array: dtype = float32 when source has no dtype)
            dtype = _np.float32
    src = src.astype(np_dtype(dtype))
    ctx = ctx or current_context()
    d = jax.device_put(src, ctx.jax_device)
    if _dm_state["on"]:
        _dm.track(d, "array")
    return NDArray(d, ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    import jax

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    ctx = ctx or current_context()
    jnp = _jnp()
    with jax.default_device(ctx.jax_device):
        d = jnp.zeros(shape, dtype=np_dtype(dtype))
    if _dm_state["on"]:
        _dm.track(d, "zeros")
    return NDArray(d, ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    import jax

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    ctx = ctx or current_context()
    jnp = _jnp()
    with jax.default_device(ctx.jax_device):
        d = jnp.ones(shape, dtype=np_dtype(dtype))
    if _dm_state["on"]:
        _dm.track(d, "ones")
    return NDArray(d, ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs):
    import jax

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    ctx = ctx or current_context()
    jnp = _jnp()
    with jax.default_device(ctx.jax_device):
        d = jnp.full(shape, val, dtype=np_dtype(dtype or "float32"))
    if _dm_state["on"]:
        _dm.track(d, "full")
    return NDArray(d, ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    return imperative_invoke("_arange", [],
                             {"start": start, "stop": stop, "step": step,
                              "repeat": repeat, "dtype": dtype})[0]


def concatenate(arrays, axis=0, always_copy=True):
    return imperative_invoke("Concat", list(arrays), {"dim": axis})[0]


def stack_arrays(arrays, axis=0):
    return imperative_invoke("stack", list(arrays), {"axis": axis})[0]


def moveaxis(tensor, source, destination):
    jnp = _jnp()
    return NDArray(jnp.moveaxis(tensor._data, source, destination), tensor._ctx)


def maximum(lhs, rhs):
    """Elementwise broadcast max of arrays/scalars (reference:
    python/mxnet/ndarray/ndarray.py:3008 maximum)."""
    return _scalar_or_broadcast(lhs, rhs, "broadcast_maximum",
                                "_maximum_scalar", max)


def minimum(lhs, rhs):
    """reference: ndarray.py:3065 minimum."""
    return _scalar_or_broadcast(lhs, rhs, "broadcast_minimum",
                                "_minimum_scalar", min)


def _scalar_or_broadcast(lhs, rhs, array_op, scalar_op, py_fn):
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return imperative_invoke(array_op, [lhs, rhs], {})[0]
    if isinstance(lhs, NDArray):
        return imperative_invoke(scalar_op, [lhs],
                                 {"scalar": float(rhs)})[0]
    if isinstance(rhs, NDArray):
        return imperative_invoke(scalar_op, [rhs],
                                 {"scalar": float(lhs)})[0]
    return py_fn(lhs, rhs)


def waitall():
    """Block until all async computation completes
    (reference: MXNDArrayWaitAll)."""
    import jax

    try:
        jax.effects_barrier()
    except Exception:
        pass


# ----------------------------------------------------------------- save/load

_MAGIC = b"MXTPU001"


def save(fname, data):
    """Serialize NDArrays (reference: src/ndarray/ndarray.cc Save/Load,
    mx.nd.save — dict or list of arrays).  Format: npz under the hood."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        arrays = {k: v.asnumpy() for k, v in data.items()}
        _np.savez(_ensure_ext(fname), __format__="dict", **arrays)
    elif isinstance(data, (list, tuple)):
        arrays = {"arr_%d" % i: v.asnumpy() for i, v in enumerate(data)}
        _np.savez(_ensure_ext(fname), __format__="list", **arrays)
    else:
        raise TypeError("save expects NDArray, list or dict")
    import os

    if os.path.exists(fname + ".npz") and not fname.endswith(".npz"):
        os.replace(fname + ".npz", fname)


def _ensure_ext(fname):
    return fname


def load_frombuffer(buf, ctx=None):
    """Deserialize an in-memory `save` blob (reference:
    MXNDArrayLoadFromBuffer, python/mxnet/ndarray/utils.py:185)."""
    import io

    return _load_npz(_np.load(io.BytesIO(bytes(buf)), allow_pickle=False),
                     ctx)


def load(fname, ctx=None):
    return _load_npz(_np.load(fname, allow_pickle=False), ctx)


def _parse_npz(data):
    """Shared save-blob format parser → numpy ('list', [...]) or
    ('dict', {...}).  Used by load/load_frombuffer and
    predictor.load_ndarray_file."""
    try:
        fmt = str(data["__format__"])
    except KeyError:
        fmt = "dict"
    if fmt == "list":
        n = len([k for k in data.files if k.startswith("arr_")])
        return "list", [data["arr_%d" % i] for i in range(n)]
    return "dict", {k: data[k] for k in data.files if k != "__format__"}


def _load_npz(data, ctx):
    fmt, parsed = _parse_npz(data)
    if fmt == "list":
        return [array(v, ctx=ctx) for v in parsed]
    return {k: array(v, ctx=ctx) for k, v in parsed.items()}
