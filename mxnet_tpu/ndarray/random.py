"""``mx.nd.random`` — random sampling functions
(reference: python/mxnet/ndarray/random.py)."""

from __future__ import annotations

import numpy as _np

from .ndarray import NDArray, imperative_invoke


def _sample(opname, shape, dtype, ctx, kwargs, tensors=()):
    # shape=() means "no tail" for the tensor-parameter _sample_* ops
    # (output shape == param shape, the reference default); only a None
    # shape falls back to the scalar-parameter default of (1,)
    if isinstance(shape, int):
        shape = (shape,)
    attrs = {"shape": tuple(shape) if shape is not None else (1,),
             "dtype": dtype or "float32"}
    attrs.update(kwargs)
    return imperative_invoke(opname, list(tensors), attrs)[0]


def _check_pair(name, a, b):
    """Tensor-parameter sampling requires ALL params as NDArrays
    (reference frontend raises the same error)."""
    if not isinstance(b, NDArray):
        raise ValueError(
            "Distribution parameters must all have the same type: %s got "
            "an NDArray and a %s" % (name, type(b).__name__))
    if isinstance(a, NDArray) and a.shape != b.shape:
        raise ValueError("Distribution parameter shapes must match: "
                         "%s vs %s" % (a.shape, b.shape))
    return b


def uniform(low=0.0, high=1.0, shape=(1,), dtype=None, ctx=None, out=None, **kwargs):
    if isinstance(low, NDArray):
        _check_pair("uniform", low, high)
        return _sample("_sample_uniform", shape if shape != (1,) else (), dtype, ctx,
                       {}, tensors=(low, high))
    return _sample("_random_uniform", shape, dtype, ctx, {"low": low, "high": high})


def normal(loc=0.0, scale=1.0, shape=(1,), dtype=None, ctx=None, out=None, **kwargs):
    if isinstance(loc, NDArray):
        _check_pair("normal", loc, scale)
        return _sample("_sample_normal", shape if shape != (1,) else (), dtype, ctx,
                       {}, tensors=(loc, scale))
    return _sample("_random_normal", shape, dtype, ctx, {"loc": loc, "scale": scale})


def randn(*shape, **kwargs):
    """numpy-style positional shape (reference: ndarray/random.py:170
    ``randn(*shape, loc=, scale=, ...)``; distinct from ``normal``,
    whose first positionals are loc/scale)."""
    if "shape" in kwargs:  # pre-r4 alias-of-normal callers
        if shape:
            raise TypeError("randn: pass the shape positionally OR as "
                            "shape=, not both")
        shape = kwargs.pop("shape")  # int or sequence; normal normalizes
    elif not all(isinstance(d, (int, _np.integer)) for d in shape):
        # a legacy randn(loc, scale) caller from the alias-of-normal era
        # must fail loudly, not sample a (loc, scale)-shaped array
        raise TypeError(
            "randn: positional args are shape dims and must be ints "
            "(got %r); pass distribution parameters as loc=/scale="
            % (shape,))
    return normal(kwargs.pop("loc", 0.0), kwargs.pop("scale", 1.0),
                  shape=shape if shape else (1,), **kwargs)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype=None, ctx=None, **kwargs):
    if isinstance(alpha, NDArray):
        _check_pair("gamma", alpha, beta)
        return _sample("_sample_gamma", shape if shape != (1,) else (), dtype, ctx,
                       {}, tensors=(alpha, beta))
    return _sample("_random_gamma", shape, dtype, ctx, {"alpha": alpha, "beta": beta})


def exponential(scale=1.0, shape=(1,), dtype=None, ctx=None, **kwargs):
    if not isinstance(scale, NDArray) and scale <= 0:
        from ..base import MXNetError

        raise MXNetError("random_exponential: invalid scale=%r" % (scale,))
    if isinstance(scale, NDArray):
        return _sample("_sample_exponential", shape if shape != (1,) else (),
                       dtype, ctx, {}, tensors=(1.0 / scale,))
    return _sample("_random_exponential", shape, dtype, ctx, {"lam": 1.0 / scale})


def poisson(lam=1.0, shape=(1,), dtype=None, ctx=None, **kwargs):
    if isinstance(lam, NDArray):
        return _sample("_sample_poisson", shape if shape != (1,) else (),
                       dtype, ctx, {}, tensors=(lam,))
    return _sample("_random_poisson", shape, dtype, ctx, {"lam": lam})


def negative_binomial(k=1, p=1.0, shape=(1,), dtype=None, ctx=None, **kwargs):
    if isinstance(k, NDArray):
        _check_pair("negative_binomial", k, p)
        return _sample("_sample_negative_binomial",
                       shape if shape != (1,) else (), dtype, ctx, {},
                       tensors=(k, p))
    return _sample("_random_negative_binomial", shape, dtype, ctx, {"k": k, "p": p})


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,), dtype=None, ctx=None,
                                  **kwargs):
    if isinstance(mu, NDArray):
        _check_pair("generalized_negative_binomial", mu, alpha)
        return _sample("_sample_generalized_negative_binomial",
                       shape if shape != (1,) else (), dtype, ctx, {},
                       tensors=(mu, alpha))
    return _sample("_random_generalized_negative_binomial", shape, dtype, ctx,
                   {"mu": mu, "alpha": alpha})


def randint(low, high, shape=(1,), dtype="int32", ctx=None, **kwargs):
    return _sample("_random_randint", shape, dtype, ctx, {"low": low, "high": high})


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kwargs):
    attrs = {"shape": (shape,) if isinstance(shape, int) else tuple(shape),
             "get_prob": get_prob, "dtype": dtype}
    res = imperative_invoke("_sample_multinomial", [data], attrs)
    # reference returns [samples, log_likelihood] when get_prob=True
    return res if get_prob else res[0]


def shuffle(data, **kwargs):
    return imperative_invoke("_shuffle", [data], {})[0]
