"""``mx.nd`` — imperative NDArray API (reference: python/mxnet/ndarray/)."""

from .. import ops as _ops  # registers all operators
from .ndarray import (NDArray, array, arange, concatenate, empty, full, load,
                      load_frombuffer, maximum, minimum,
                      moveaxis, ones, save, waitall, zeros,
                      imperative_invoke)
from .register import populate as _populate

_populate(globals())

# `stack` op func from registry shadows nothing; keep `stack_arrays` too
from .ndarray import stack_arrays  # noqa: E402,F401

from . import random  # noqa: E402,F401
from . import sparse  # noqa: E402,F401


def onehot_encode(indices, out):
    """reference: mx.nd.onehot_encode legacy helper."""
    res = imperative_invoke("one_hot", [indices], {"depth": out.shape[1]})[0]
    out._assign(res._data.astype(out.dtype))
    return out

from . import contrib  # noqa: E402,F401
