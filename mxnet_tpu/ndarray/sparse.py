"""Sparse NDArray storage types: row_sparse and CSR.

Reference: include/mxnet/ndarray.h:61 (NDArrayStorageType),
python/mxnet/ndarray/sparse.py, src/operator/tensor/cast_storage-inl.h.

TPU-native stance: XLA has no first-class sparse buffers; row_sparse is
a REAL (indices, values) pair on device — the dense view is LAZY and
materializes only when a dense consumer touches it (XLA scatter at that
boundary).  The embedding-scale flows the type exists for (reference:
kvstore_dist.h:470 PullRowSparse; lazy optimizer rows) run entirely on
the (indices, values) pair, so a gradient over a 10M-row table costs
memory proportional to the touched rows, not the table.  CSR keeps the
r1 dense-backed layout (its reference uses are small matrices).
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError, np_dtype
from .ndarray import NDArray, array, imperative_invoke, zeros as _dense_zeros


class BaseSparseNDArray(NDArray):
    __slots__ = ("_stype", "_aux")

    @property
    def stype(self):
        return self._stype


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: a device (indices into dim0, values for those rows)
    pair.  The dense view is lazy — see module docstring."""

    __slots__ = ("_dense_cache", "_rs_shape")

    def __init__(self, data, indices, shape, ctx=None):
        # deliberately NOT NDArray.__init__: no dense materialization
        self._dense_cache = None
        self._rs_shape = tuple(int(d) for d in shape)
        self._ctx = ctx
        self._ag_node = None
        self._writeback = None
        self._stype = "row_sparse"
        self._aux = (indices, data)

    # -- lazy dense view ---------------------------------------------------
    @property
    def _data(self):
        if self._dense_cache is None:
            import jax.numpy as jnp

            idx, vals = self._aux
            self._dense_cache = jnp.zeros(
                self._rs_shape, dtype=vals.dtype).at[idx].set(vals)
        return self._dense_cache

    @_data.setter
    def _data(self, value):  # _assign() writes through here
        # keep the sparse view consistent: re-derive (indices, values)
        # from the new dense content (device-side nonzero-row scan, the
        # cast_storage kernel); the caller already holds the dense array
        import jax.numpy as jnp

        self._dense_cache = value
        if value.ndim > 1:
            mask = jnp.any(value != 0, axis=tuple(range(1, value.ndim)))
        else:
            mask = value != 0
        idx = jnp.nonzero(mask)[0]
        self._aux = (idx, value[idx])

    @property
    def densified(self):
        """Whether the dense view has been materialized (diagnostic)."""
        return self._dense_cache is not None

    # shape/dtype must not force materialization
    @property
    def shape(self):
        return self._rs_shape

    @property
    def dtype(self):
        return _np.dtype(self._aux[1].dtype)

    @property
    def size(self):
        n = 1
        for d in self._rs_shape:
            n *= d
        return n

    @property
    def ndim(self):
        return len(self._rs_shape)

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        from .ndarray import NDArray as _ND

        return _ND(self._aux[1], self._ctx).context

    ctx = context

    def wait_to_read(self):
        self._aux[1].block_until_ready()

    wait_to_write = wait_to_read

    def astype(self, dtype, copy=True):
        d = np_dtype(dtype)
        if not copy and self.dtype == d:
            return self
        return RowSparseNDArray(self._aux[1].astype(d), self._aux[0],
                                self._rs_shape, self._ctx)

    @property
    def indices(self):
        return NDArray(self._aux[0], self._ctx)

    @property
    def data(self):
        return NDArray(self._aux[1], self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError("cast row_sparse→%s unsupported" % stype)

    def retain(self, indices):
        return retain(self, indices)

    def copyto(self, other):
        if isinstance(other, NDArray) and not isinstance(
                other, BaseSparseNDArray):
            if tuple(other.shape) != tuple(self.shape):
                raise ValueError("copyto shape mismatch: %s vs %s"
                                 % (self.shape, other.shape))
            other._assign(self._data)
            return other
        return super().copyto(other)

    @classmethod
    def _from_dense(cls, dense_jax, idx_jax, ctx):
        """Wrap an existing dense device array + row indices without any
        host round-trip (device-side cast_storage fast path)."""
        rsp = cls(dense_jax[idx_jax], idx_jax, dense_jax.shape, ctx)
        rsp._dense_cache = dense_jax  # already materialized by caller
        return rsp


class CSRNDArray(BaseSparseNDArray):
    def __init__(self, data, indices, indptr, shape, ctx=None):
        import jax.numpy as jnp

        dense = _np.zeros(shape, dtype=_np.asarray(data).dtype)
        d = _np.asarray(data)
        ind = _np.asarray(indices).astype(_np.int64)
        ptr = _np.asarray(indptr).astype(_np.int64)
        for row in range(shape[0]):
            lo, hi = ptr[row], ptr[row + 1]
            dense[row, ind[lo:hi]] = d[lo:hi]
        super().__init__(jnp.asarray(dense), ctx)
        self._stype = "csr"
        self._aux = (d, ind, ptr)

    @property
    def data(self):
        return array(self._aux[0], ctx=self._ctx)

    @property
    def indices(self):
        return array(self._aux[1], ctx=self._ctx)

    @property
    def indptr(self):
        return array(self._aux[2], ctx=self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError("cast csr→%s unsupported" % stype)

    def __getitem__(self, key):
        """Row slicing keeps CSR (reference: sparse.py CSRNDArray.__getitem__)."""
        if isinstance(key, slice):
            start, stop, step = key.indices(self.shape[0])
            if step != 1:
                raise MXNetError("csr slicing requires step 1")
            stop = max(stop, start)  # empty slice -> empty CSR, like numpy
            d, ind, ptr = self._aux
            lo, hi = int(ptr[start]), int(ptr[stop])
            new_ptr = ptr[start:stop + 1] - ptr[start]
            return CSRNDArray(d[lo:hi], ind[lo:hi], new_ptr,
                              (stop - start,) + tuple(self.shape[1:]),
                              self._ctx)
        return super().__getitem__(key)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        import jax.numpy as jnp

        d = jnp.asarray(_np.asarray(data, dtype=np_dtype(dtype)))
        i = jnp.asarray(_np.asarray(indices, dtype=_np.int64))
        return RowSparseNDArray(d, i, shape, ctx)
    dense = _np.asarray(arg1, dtype=np_dtype(dtype))
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    import jax.numpy as jnp

    return RowSparseNDArray(jnp.asarray(dense[nz]), jnp.asarray(nz),
                            dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indices, indptr, shape, ctx)
    dense = _np.asarray(arg1, dtype=np_dtype(dtype))
    indptr = [0]
    indices = []
    data = []
    for row in dense:
        nz = _np.where(row != 0)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(_np.asarray(data, dtype=dense.dtype),
                      _np.asarray(indices), _np.asarray(indptr), dense.shape, ctx)


def cast_storage(arr, stype):
    if stype == "default":
        return NDArray(arr._data, arr._ctx)
    if stype == "row_sparse":
        # device-side: nonzero-row scan runs on the accelerator; only the
        # (small) index vector ever syncs (reference: cast_storage-inl.h
        # CastStorageDnsRspImpl, also a device kernel)
        import jax.numpy as jnp

        data = arr._data
        if data.ndim > 1:
            mask = jnp.any(data != 0,
                           axis=tuple(range(1, data.ndim)))
        else:
            mask = data != 0
        idx = jnp.nonzero(mask)[0]
        return RowSparseNDArray._from_dense(data, idx, arr._ctx)
    if stype == "csr":
        dense = arr.asnumpy()
        return csr_matrix(dense, shape=dense.shape, ctx=arr._ctx, dtype=dense.dtype)
    raise MXNetError("unknown stype %r" % stype)


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "default":
        return _dense_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "row_sparse":
        # all-zero rsp = empty (indices, values): allocates nothing
        import jax.numpy as jnp

        dt = np_dtype(dtype)
        return RowSparseNDArray(
            jnp.zeros((0,) + tuple(shape[1:]), dtype=dt),
            jnp.zeros((0,), dtype=jnp.int32), shape, ctx)
    z = _np.zeros(shape, dtype=np_dtype(dtype))
    return cast_storage(array(z, ctx=ctx), stype)


# -------------------------------------------------------------- operators
# Reference: src/operator/tensor/ sparse FComputeEx kernels (dot, retain,
# elemwise with stype inference).  Dense-backed arrays mean the math runs
# on the MXU; what these preserve is the STORAGE-TYPE SEMANTICS — output
# stypes follow the reference's storage-inference rules so downstream
# sparse-aware code (kvstore row_sparse flows, lazy optimizers) keeps
# working.

def retain(rsp, indices):
    """Keep only `indices` rows of a row_sparse array (reference:
    _retain sparse_retain-inl.h).  Touches only the (indices, values)
    pair — never the dense view."""
    if getattr(rsp, "stype", None) != "row_sparse":
        raise MXNetError("retain expects a row_sparse array")
    idx = indices.asnumpy().astype(_np.int64) if isinstance(indices, NDArray) \
        else _np.asarray(indices, dtype=_np.int64)
    old_idx = _np.asarray(rsp._aux[0])
    old_val = rsp._aux[1]
    keep = _np.where(_np.isin(old_idx, idx))[0]
    import jax.numpy as jnp

    return RowSparseNDArray(old_val[jnp.asarray(keep)],
                            jnp.asarray(old_idx[keep]), rsp.shape, rsp._ctx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (reference: src/operator/tensor/dot-inl.h).

    csr × dense -> dense; csrᵀ × dense -> row_sparse (the embedding-
    gradient shape, reference DotCsrTransDnsRspImpl)."""
    from ..ops.registry import apply_op

    l_stype = getattr(lhs, "stype", "default")
    out = apply_op("dot", lhs._data, rhs._data,
                   transpose_a=transpose_a, transpose_b=transpose_b)
    if l_stype == "csr" and transpose_a:
        dense = NDArray(out, lhs._ctx)
        return cast_storage(dense, "row_sparse")
    return NDArray(out, lhs._ctx)


def _ew(opname, lhs, rhs):
    from ..ops.registry import apply_op

    out = NDArray(apply_op(opname, lhs._data, rhs._data), lhs._ctx)
    ls = getattr(lhs, "stype", "default")
    rs = getattr(rhs, "stype", "default")
    # reference storage inference: rsp⊕rsp -> rsp (add/sub); anything with
    # dense -> dense
    if ls == rs == "row_sparse" and opname in ("elemwise_add",
                                               "elemwise_sub"):
        return cast_storage(out, "row_sparse")
    return out


def add(lhs, rhs):
    return _ew("elemwise_add", lhs, rhs)


def subtract(lhs, rhs):
    return _ew("elemwise_sub", lhs, rhs)


def multiply(lhs, rhs):
    return _ew("elemwise_mul", lhs, rhs)


def elemwise_add(lhs, rhs):
    return _ew("elemwise_add", lhs, rhs)


def elemwise_sub(lhs, rhs):
    return _ew("elemwise_sub", lhs, rhs)


def elemwise_mul(lhs, rhs):
    return _ew("elemwise_mul", lhs, rhs)
