"""Sparse NDArray storage types: row_sparse and CSR.

Reference: include/mxnet/ndarray.h:61 (NDArrayStorageType),
python/mxnet/ndarray/sparse.py, src/operator/tensor/cast_storage-inl.h.

TPU-native stance: XLA has no first-class sparse buffers; row_sparse is
represented as (indices, values) host-side metadata over dense jax
arrays and converts to dense at op boundaries (XLA scatter/gather).
This gives API parity for embedding/optimizer flows
(``row_sparse_pull``); kernels stay dense-MXU friendly.
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError, np_dtype
from .ndarray import NDArray, array, imperative_invoke, zeros as _dense_zeros


class BaseSparseNDArray(NDArray):
    __slots__ = ("_stype", "_aux")

    @property
    def stype(self):
        return self._stype


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: (indices into dim0, values for those rows)."""

    def __init__(self, data, indices, shape, ctx=None):
        import jax.numpy as jnp

        dense = jnp.zeros(shape, dtype=data.dtype).at[indices].set(data)
        super().__init__(dense, ctx)
        self._stype = "row_sparse"
        self._aux = (indices, data)

    @property
    def indices(self):
        return NDArray(self._aux[0], self._ctx)

    @property
    def data(self):
        return NDArray(self._aux[1], self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError("cast row_sparse→%s unsupported" % stype)


class CSRNDArray(BaseSparseNDArray):
    def __init__(self, data, indices, indptr, shape, ctx=None):
        import jax.numpy as jnp

        dense = _np.zeros(shape, dtype=_np.asarray(data).dtype)
        d = _np.asarray(data)
        ind = _np.asarray(indices).astype(_np.int64)
        ptr = _np.asarray(indptr).astype(_np.int64)
        for row in range(shape[0]):
            lo, hi = ptr[row], ptr[row + 1]
            dense[row, ind[lo:hi]] = d[lo:hi]
        super().__init__(jnp.asarray(dense), ctx)
        self._stype = "csr"
        self._aux = (d, ind, ptr)

    @property
    def data(self):
        return array(self._aux[0], ctx=self._ctx)

    @property
    def indices(self):
        return array(self._aux[1], ctx=self._ctx)

    @property
    def indptr(self):
        return array(self._aux[2], ctx=self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError("cast csr→%s unsupported" % stype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        import jax.numpy as jnp

        d = jnp.asarray(_np.asarray(data, dtype=np_dtype(dtype)))
        i = jnp.asarray(_np.asarray(indices, dtype=_np.int64))
        return RowSparseNDArray(d, i, shape, ctx)
    dense = _np.asarray(arg1, dtype=np_dtype(dtype))
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    import jax.numpy as jnp

    return RowSparseNDArray(jnp.asarray(dense[nz]), jnp.asarray(nz),
                            dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indices, indptr, shape, ctx)
    dense = _np.asarray(arg1, dtype=np_dtype(dtype))
    indptr = [0]
    indices = []
    data = []
    for row in dense:
        nz = _np.where(row != 0)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(_np.asarray(data, dtype=dense.dtype),
                      _np.asarray(indices), _np.asarray(indptr), dense.shape, ctx)


def cast_storage(arr, stype):
    if stype == "default":
        return NDArray(arr._data, arr._ctx)
    if stype == "row_sparse":
        dense = arr.asnumpy()
        return row_sparse_array(dense, shape=dense.shape, ctx=arr._ctx,
                                dtype=dense.dtype)
    if stype == "csr":
        dense = arr.asnumpy()
        return csr_matrix(dense, shape=dense.shape, ctx=arr._ctx, dtype=dense.dtype)
    raise MXNetError("unknown stype %r" % stype)


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "default":
        return _dense_zeros(shape, ctx=ctx, dtype=dtype)
    z = _np.zeros(shape, dtype=np_dtype(dtype))
    return cast_storage(array(z, ctx=ctx), stype)
