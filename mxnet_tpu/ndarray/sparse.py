"""Sparse NDArray storage types: row_sparse and CSR.

Reference: include/mxnet/ndarray.h:61 (NDArrayStorageType),
python/mxnet/ndarray/sparse.py, src/operator/tensor/cast_storage-inl.h.

TPU-native stance: XLA has no first-class sparse buffers; both storage
types are REAL device aux-array tuples with a LAZY dense view that
materializes only when a dense consumer touches it (XLA scatter at that
boundary).  row_sparse is an (indices, values) pair — the embedding-
scale flows it exists for (reference: kvstore_dist.h:470 PullRowSparse;
lazy optimizer rows) cost memory proportional to the touched rows.
CSR (r3) is a (data, indices, indptr) triple; `sparse.dot` runs
gather + segment-sum kernels over it in O(nnz·k), so a LibSVM-scale
design matrix never allocates its m×n dense form.
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError, np_dtype
from .ndarray import NDArray, array, imperative_invoke, zeros as _dense_zeros


class BaseSparseNDArray(NDArray):
    """Shared surface of the lazy-dense sparse arrays: aux device
    arrays + logical shape, with every shape/dtype/sync accessor
    guaranteed not to force dense materialization."""

    __slots__ = ("_stype", "_aux", "_dense_cache", "_sp_shape")

    def _init_sparse(self, stype, aux, shape, ctx):
        # deliberately NOT NDArray.__init__: no dense materialization
        self._dense_cache = None
        self._sp_shape = tuple(int(d) for d in shape)
        self._ctx = ctx
        self._ag_node = None
        self._writeback = None
        self._stype = stype
        self._aux = aux

    def _values(self):
        """The values aux array (subclass-specific position)."""
        raise NotImplementedError

    @property
    def stype(self):
        return self._stype

    @property
    def densified(self):
        """Whether the dense view has been materialized (diagnostic)."""
        return self._dense_cache is not None

    @property
    def shape(self):
        return self._sp_shape

    @property
    def dtype(self):
        return _np.dtype(self._values().dtype)

    @property
    def size(self):
        n = 1
        for d in self._sp_shape:
            n *= d
        return n

    @property
    def ndim(self):
        return len(self._sp_shape)

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        return NDArray(self._values(), self._ctx).context

    ctx = context

    def wait_to_read(self):
        self._values().block_until_ready()

    wait_to_write = wait_to_read

    def copyto(self, other):
        if isinstance(other, NDArray) and not isinstance(
                other, BaseSparseNDArray):
            if tuple(other.shape) != tuple(self.shape):
                raise ValueError("copyto shape mismatch: %s vs %s"
                                 % (self.shape, other.shape))
            other._assign(self._data)
            return other
        return super().copyto(other)


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: a device (indices into dim0, values for those rows)
    pair.  The dense view is lazy — see module docstring."""

    __slots__ = ()

    def __init__(self, data, indices, shape, ctx=None):
        self._init_sparse("row_sparse", (indices, data), shape, ctx)

    def _values(self):
        return self._aux[1]

    # -- lazy dense view ---------------------------------------------------
    @property
    def _data(self):
        if self._dense_cache is None:
            import jax.numpy as jnp

            idx, vals = self._aux
            self._dense_cache = jnp.zeros(
                self._sp_shape, dtype=vals.dtype).at[idx].set(vals)
        return self._dense_cache

    @_data.setter
    def _data(self, value):  # _assign() writes through here
        # keep the sparse view consistent: re-derive (indices, values)
        # from the new dense content (device-side nonzero-row scan, the
        # cast_storage kernel); the caller already holds the dense array
        import jax.numpy as jnp

        self._dense_cache = value
        if value.ndim > 1:
            mask = jnp.any(value != 0, axis=tuple(range(1, value.ndim)))
        else:
            mask = value != 0
        idx = jnp.nonzero(mask)[0]
        self._aux = (idx, value[idx])

    def astype(self, dtype, copy=True):
        d = np_dtype(dtype)
        if not copy and self.dtype == d:
            return self
        return RowSparseNDArray(self._aux[1].astype(d), self._aux[0],
                                self._sp_shape, self._ctx)

    @property
    def indices(self):
        return NDArray(self._aux[0], self._ctx)

    @property
    def data(self):
        return NDArray(self._aux[1], self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError("cast row_sparse→%s unsupported" % stype)

    def retain(self, indices):
        return retain(self, indices)

    @classmethod
    def _from_dense(cls, dense_jax, idx_jax, ctx):
        """Wrap an existing dense device array + row indices without any
        host round-trip (device-side cast_storage fast path)."""
        rsp = cls(dense_jax[idx_jax], idx_jax, dense_jax.shape, ctx)
        rsp._dense_cache = dense_jax  # already materialized by caller
        return rsp


class CSRNDArray(BaseSparseNDArray):
    """CSR: a REAL device (data, indices, indptr) triple (r3; reference:
    python/mxnet/ndarray/sparse.py:287 CSRNDArray over the same three
    aux arrays).  Like RowSparseNDArray, the dense view is LAZY — it
    materializes only when a dense consumer touches ``_data``, so a
    LibSVM-scale matrix (say 2^17 × 2^17, nnz ≪ m·n) lives on device in
    O(nnz) memory and `sparse.dot` runs without ever allocating m·n."""

    __slots__ = ()

    def __init__(self, data, indices, indptr, shape, ctx=None):
        import jax.numpy as jnp

        def dev(x, want_int=False):
            # accept numpy / lists / NDArray / jax arrays uniformly
            x = getattr(x, "_data", x)
            x = jnp.asarray(_np.asarray(x, dtype=_np.int32)
                            if want_int and not hasattr(x, "devices")
                            else x)
            return x.astype(jnp.int32) if want_int and x.dtype not in (
                jnp.int32, jnp.int64) else x

        d = dev(data)
        ind = dev(indices, want_int=True)
        ptr = dev(indptr, want_int=True)
        if int(ptr.shape[0]) != int(shape[0]) + 1:
            raise MXNetError("indptr length %d != rows+1 (%d)"
                             % (int(ptr.shape[0]), int(shape[0]) + 1))
        self._init_sparse("csr", (d, ind, ptr), shape, ctx)

    def _values(self):
        return self._aux[0]

    def _row_ids(self):
        """Row id of every stored value: the CSR expansion
        searchsorted(indptr, k, 'right')-1 — static-shaped, runs on
        device."""
        import jax.numpy as jnp

        d, _, ptr = self._aux
        nnz = int(d.shape[0])
        return jnp.searchsorted(ptr, jnp.arange(nnz, dtype=ptr.dtype),
                                side="right") - 1

    # -- lazy dense view ---------------------------------------------------
    @property
    def _data(self):
        if self._dense_cache is None:
            import jax.numpy as jnp

            d, ind, _ = self._aux
            self._dense_cache = jnp.zeros(
                self._sp_shape, dtype=d.dtype).at[self._row_ids(), ind].add(d)
        return self._dense_cache

    @_data.setter
    def _data(self, value):  # _assign() writes through here
        # device-side re-derivation (mirrors the RowSparse setter):
        # jnp.nonzero syncs only the nnz count, not the dense payload
        import jax.numpy as jnp

        self._dense_cache = value
        rows, cols = jnp.nonzero(value)
        counts = jnp.bincount(rows, length=value.shape[0])
        ptr = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(counts).astype(jnp.int32)])
        self._aux = (value[rows, cols], cols.astype(jnp.int32), ptr)

    def astype(self, dtype, copy=True):
        d = np_dtype(dtype)
        if not copy and self.dtype == d:
            return self
        return CSRNDArray(self._aux[0].astype(d), self._aux[1],
                          self._aux[2], self._sp_shape, self._ctx)

    @property
    def data(self):
        return NDArray(self._aux[0], self._ctx)

    @property
    def indices(self):
        return NDArray(self._aux[1], self._ctx)

    @property
    def indptr(self):
        return NDArray(self._aux[2], self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError("cast csr→%s unsupported" % stype)

    def __getitem__(self, key):
        """Row slicing keeps CSR (reference: sparse.py CSRNDArray.__getitem__).

        The slice bounds sync two indptr scalars to host (variable nnz
        — inherently data-dependent, same as the reference)."""
        if isinstance(key, slice):
            start, stop, step = key.indices(self.shape[0])
            if step != 1:
                raise MXNetError("csr slicing requires step 1")
            stop = max(stop, start)  # empty slice -> empty CSR, like numpy
            d, ind, ptr = self._aux
            lo, hi = int(ptr[start]), int(ptr[stop])
            new_ptr = ptr[start:stop + 1] - lo
            return CSRNDArray(d[lo:hi], ind[lo:hi], new_ptr,
                              (stop - start,) + tuple(self.shape[1:]),
                              self._ctx)
        return super().__getitem__(key)


def _csr_parts_from_dense(dense):
    """Host CSR expansion of a dense numpy array (vectorized)."""
    rows, cols = _np.nonzero(dense)
    data = dense[rows, cols]
    indptr = _np.zeros(dense.shape[0] + 1, dtype=_np.int32)
    _np.add.at(indptr, rows + 1, 1)
    indptr = _np.cumsum(indptr).astype(_np.int32)
    return (data, cols.astype(_np.int32), indptr)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        import jax.numpy as jnp

        d = jnp.asarray(_np.asarray(data, dtype=np_dtype(dtype)))
        i = jnp.asarray(_np.asarray(indices, dtype=_np.int64))
        return RowSparseNDArray(d, i, shape, ctx)
    dense = _np.asarray(arg1, dtype=np_dtype(dtype))
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    import jax.numpy as jnp

    return RowSparseNDArray(jnp.asarray(dense[nz]), jnp.asarray(nz),
                            dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indices, indptr, shape, ctx)
    dense = _np.asarray(arg1, dtype=np_dtype(dtype))
    data, indices, indptr = _csr_parts_from_dense(dense)
    return CSRNDArray(data, indices, indptr, dense.shape, ctx)


def cast_storage(arr, stype):
    if stype == "default":
        return NDArray(arr._data, arr._ctx)
    if stype == "row_sparse":
        # device-side: nonzero-row scan runs on the accelerator; only the
        # (small) index vector ever syncs (reference: cast_storage-inl.h
        # CastStorageDnsRspImpl, also a device kernel)
        import jax.numpy as jnp

        data = arr._data
        if data.ndim > 1:
            mask = jnp.any(data != 0,
                           axis=tuple(range(1, data.ndim)))
        else:
            mask = data != 0
        idx = jnp.nonzero(mask)[0]
        return RowSparseNDArray._from_dense(data, idx, arr._ctx)
    if stype == "csr":
        # dense->CSR is a by-design materialization point: CSR storage
        # is host-backed (indptr/indices live in host numpy), so the
        # explicit tostype('csr') conversion IS the sync
        host = arr.asnumpy()  # mxlint: disable=host-sync-reachability -- CSR is host-backed by design
        csr = csr_matrix(host, ctx=arr._ctx, dtype=host.dtype)
        csr._dense_cache = arr._data  # already materialized by caller
        return csr
    raise MXNetError("unknown stype %r" % stype)


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "default":
        return _dense_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "row_sparse":
        # all-zero rsp = empty (indices, values): allocates nothing
        import jax.numpy as jnp

        dt = np_dtype(dtype)
        return RowSparseNDArray(
            jnp.zeros((0,) + tuple(shape[1:]), dtype=dt),
            jnp.zeros((0,), dtype=jnp.int32), shape, ctx)
    z = _np.zeros(shape, dtype=np_dtype(dtype))
    return cast_storage(array(z, ctx=ctx), stype)


# -------------------------------------------------------------- operators
# Reference: src/operator/tensor/ sparse FComputeEx kernels (dot, retain,
# elemwise with stype inference).  Dense-backed arrays mean the math runs
# on the MXU; what these preserve is the STORAGE-TYPE SEMANTICS — output
# stypes follow the reference's storage-inference rules so downstream
# sparse-aware code (kvstore row_sparse flows, lazy optimizers) keeps
# working.

def retain(rsp, indices):
    """Keep only `indices` rows of a row_sparse array (reference:
    _retain sparse_retain-inl.h).  Touches only the (indices, values)
    pair — never the dense view."""
    if getattr(rsp, "stype", None) != "row_sparse":
        raise MXNetError("retain expects a row_sparse array")
    idx = indices.asnumpy().astype(_np.int64) if isinstance(indices, NDArray) \
        else _np.asarray(indices, dtype=_np.int64)
    old_idx = _np.asarray(rsp._aux[0])
    old_val = rsp._aux[1]
    keep = _np.where(_np.isin(old_idx, idx))[0]
    import jax.numpy as jnp

    return RowSparseNDArray(old_val[jnp.asarray(keep)],
                            jnp.asarray(old_idx[keep]), rsp.shape, rsp._ctx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (reference: src/operator/tensor/dot-inl.h).

    csr × dense -> dense and csrᵀ × dense -> row_sparse (the embedding-
    gradient shape, reference DotCsrDnsDnsImpl / DotCsrTransDnsRspImpl)
    run REAL sparse kernels on the (data, indices, indptr) triple —
    gather + segment-sum, O(nnz·k) work, never materializing the m×n
    dense lhs.  Static shapes throughout (nnz is the array's stored
    size), so XLA compiles one program per CSR geometry."""
    l_stype = getattr(lhs, "stype", "default")
    if l_stype == "csr":
        return _dot_csr(lhs, rhs, transpose_a, transpose_b)
    from ..ops.registry import apply_op

    out = apply_op("dot", lhs._data, rhs._data,
                   transpose_a=transpose_a, transpose_b=transpose_b)
    return NDArray(out, lhs._ctx)


def _dot_csr(lhs, rhs, transpose_a, transpose_b):
    import jax
    import jax.numpy as jnp

    d, ind, _ = lhs._aux
    rows = lhs._row_ids()
    r = rhs._data
    if transpose_b:
        r = r.T
    vec = r.ndim == 1
    if vec:
        r = r[:, None]   # matvec: compute as (n, 1) and squeeze
    if r.ndim != 2:
        raise MXNetError("csr dot needs a 1-D or 2-D rhs")
    m, n = lhs.shape
    if not transpose_a:
        if int(r.shape[0]) != n:
            raise MXNetError("csr dot shape mismatch: %s x %s"
                             % (lhs.shape, r.shape))
        # y[row] += data[k] * rhs[col(k)]  (gather rows of rhs, segment-
        # sum by CSR row id; reference DotCsrDnsDnsImpl)
        contrib = d[:, None] * r[ind]
        out = jax.ops.segment_sum(contrib, rows, num_segments=m)
        return NDArray(out[:, 0] if vec else out, lhs._ctx)
    if int(r.shape[0]) != m:
        raise MXNetError("csr^T dot shape mismatch: %s^T x %s"
                         % (lhs.shape, r.shape))
    # out[col(k)] += data[k] * rhs[row(k)] — scatter-add into the (n, k)
    # gradient; row_sparse result (reference DotCsrTransDnsRspImpl)
    contrib = d[:, None] * r[rows]
    out = jnp.zeros((n, r.shape[1]), dtype=contrib.dtype).at[ind].add(contrib)
    if vec:
        return NDArray(out[:, 0], lhs._ctx)
    touched = jnp.zeros((n,), dtype=jnp.bool_).at[ind].set(True)
    idx = jnp.nonzero(touched)[0]
    return RowSparseNDArray._from_dense(out, idx, lhs._ctx)


def _ew(opname, lhs, rhs):
    from ..ops.registry import apply_op

    out = NDArray(apply_op(opname, lhs._data, rhs._data), lhs._ctx)
    ls = getattr(lhs, "stype", "default")
    rs = getattr(rhs, "stype", "default")
    # reference storage inference (ElemwiseStorageType): same sparse
    # stype in -> same stype out for add/sub/mul; anything with a dense
    # operand -> dense.  (mul of two sparse is sparse since the product
    # vanishes wherever either operand does.)
    if ls == rs and ls in ("row_sparse", "csr") and opname in (
            "elemwise_add", "elemwise_sub", "elemwise_mul"):
        return cast_storage(out, ls)
    return out


def add(lhs, rhs):
    return _ew("elemwise_add", lhs, rhs)


def subtract(lhs, rhs):
    return _ew("elemwise_sub", lhs, rhs)


def multiply(lhs, rhs):
    return _ew("elemwise_mul", lhs, rhs)


def elemwise_add(lhs, rhs):
    return _ew("elemwise_add", lhs, rhs)


def elemwise_sub(lhs, rhs):
    return _ew("elemwise_sub", lhs, rhs)


def elemwise_mul(lhs, rhs):
    return _ew("elemwise_mul", lhs, rhs)
