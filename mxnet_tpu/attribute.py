"""Attribute scoping (reference: python/mxnet/attribute.py).

The implementation lives in ``mxnet_tpu.base``; this module keeps the
reference import path ``from mxnet.attribute import AttrScope``.
"""

from .base import AttrScope  # noqa: F401
