"""Whole-step compilation — forward+backward+update as ONE XLA program.

On the eager mainline every op is its own cached ``jax.jit``
executable: XLA can only fuse inside op boundaries, and ``stepstats``
shows ``dispatch_warm`` as a standing per-step tax (one host dispatch
per op per step).  Per the Julia→TPU full-compilation result
(arXiv:1810.09868) and the XLA fusion analysis (arXiv:2301.13062), the
win comes from handing XLA the *whole* training step: this module
traces the hybridized forward, the loss, the backward
(``jax.value_and_grad``), and the REAL optimizer update — the same
``Updater``/fused-kernel path ``gluon.Trainer`` runs, not a hand-rolled
SGD — into one jitted program with **donated** parameter / optimizer
/ aux buffers, so the update is in-place on device, cross-op fusion is
free, and the per-step host cost amortizes to ~one dispatch.

Contract
--------
- ``compile_step(block, loss, trainer)`` (or ``trainer.compile(block,
  loss)``) returns a :class:`CompiledStep`; ``cs.step(x, y)`` replaces
  the whole ``record()/backward()/trainer.step()`` iteration and
  returns the loss block's output (per-sample losses, async).
- Programs are cached per ``(batch shape, dtype, rescale_grad)`` like
  the dispatch layer's per-op jit cache: a shape change builds a new
  entry (counted as a ``compiled_step`` jit-cache miss, visible to the
  recompile-storm detector), it never silently retraces per step.
- **Donation/rebind**: the params', optimizer states', and aux states'
  device buffers are donated into each call (XLA reuses them for the
  outputs — no 2x working set) and the fresh outputs are rebound into
  the same ``NDArray`` objects before ``step()`` returns.  Everything
  that reads those NDArrays afterwards — checkpointing, health hooks,
  ``save_parameters``, eager evaluation — sees the updated values;
  *other* NDArray handles aliasing the old buffers are invalidated,
  like any in-place update.
- **Per-step scalars** (scheduler lr, Adam bias correction, FTML /
  Adamax ``t``) are recomputed host-side each step by
  ``Optimizer.step_scalars`` — the same double-precision host math the
  eager path runs — and fed into the program as traced arguments
  (``optimizer.scalar_feed``), so schedules never recompile and eager
  vs compiled numerics agree to the bit for the fused-kernel
  optimizers.
- Supported optimizers declare ``compiled_step_safe = True`` (SGD,
  NAG, Signum, Adam, Adamax, FTML, Ftrl, RMSProp, AdaGrad, AdaDelta);
  the rest — host syncs (LBSGD), cross-step host recurrences (Nadam),
  raw host-scalar NDArray math — keep the eager path and raise a clear
  error here.
- ``compile_step(..., zero=True)`` / ``MXNET_TPU_ZERO=1`` routes the
  same seam through :class:`ZeroCompiledStep`: the fused program with
  ZeRO weight-update sharding over the 'dp' mesh axis — grads
  reduce-scattered to 1/n shards, the update on each device's
  param+state shard, updated params all-gathered inside the program
  (parallel/gluon_step.py zero path; docs/ZERO.md).
- The eager path stays the untouched default and the
  debugging/interop mode; ``MXNET_TPU_COMPILED_STEP=1``
  (:func:`env_enabled`) is the opt-in for bench/launch wiring.

Observability: each ``step()`` emits the same ``trainer:step``
span/histogram as the eager Trainer, counts ``trainer_steps`` /
``compiled_step_steps``, feeds the dedicated ``compiled_step``
stepstats phase when dispatch timing is on, registers entry builds as
``compiled_step`` jit-cache misses with their compile seconds, and
captures the program's XLA cost/memory analysis into the diag dump's
cost section when cost capture is active (the per-op jit-entry
convention).  Docs: docs/COMPILED_STEP.md.
"""

from __future__ import annotations

import os
import weakref

from . import health as _health
from . import profiler as _prof
from . import random as _random
from . import runtime_stats as _rts
from . import xray as _xray
from .base import MXNetError
from .ndarray import NDArray
from .optimizer import optimizer as _opt
from .ops import registry as _registry

__all__ = ["CompiledStep", "ZeroCompiledStep", "compile_step",
           "env_enabled", "donation_active", "cost_snapshot",
           "xray_snapshot"]

# live CompiledStep instances, for the read-side cost aggregation
# (runtime_stats.snapshot merges cost_snapshot() into its "costs"
# section) — weak so a dropped step never outlives its model
_LIVE: "weakref.WeakSet[CompiledStep]" = weakref.WeakSet()

# flips True the first time buffers are handed to a donating program
# call and stays: by-reference checkpoint captures must pin
# (materialize) from then on, because later steps donate the
# param/optimizer buffers regardless of Python references
# (checkpoint.save_trainer consults this); a failed build or guard
# never donated, so it never forces pinning
_state = {"donating": False}


def donation_active():
    """True once any CompiledStep has stepped in this process — device
    buffers captured by reference may be donated (invalidated) by a
    later step, so zero-copy snapshot captures must materialize at
    capture time."""
    return _state["donating"]


def env_enabled():
    """True when ``MXNET_TPU_COMPILED_STEP=1`` asks launch/bench wiring
    to train through the compiled whole-step path."""
    return os.environ.get("MXNET_TPU_COMPILED_STEP") == "1"


def compile_step(block, loss, trainer, zero=None, mesh=None):
    """Compile ``block`` + ``loss`` + ``trainer``'s optimizer into one
    donated whole-step XLA program (see module docstring).

    ``zero=True`` (default from ``MXNET_TPU_ZERO=1``) routes through
    :class:`ZeroCompiledStep` — the same fused program with ZeRO
    weight-update sharding over the 'dp' mesh axis (docs/ZERO.md);
    ``mesh`` optionally pins the device mesh for that path."""
    if zero is None:
        from .parallel.gluon_step import zero_env_enabled
        zero = zero_env_enabled()
    if zero:
        return ZeroCompiledStep(block, loss, trainer, mesh=mesh)
    return CompiledStep(block, loss, trainer)


def _guard_trainer(trainer, zero=False):
    """The shared compile-time eligibility checks: a traceable
    fused-kernel optimizer, updates running locally (not on kvstore
    servers / across processes), and — for the single-program
    replicated path only — a single context."""
    opt = trainer._optimizer
    if not getattr(opt, "compiled_step_safe", False):
        raise MXNetError(
            "compiled_step: optimizer %s is not compiled-step safe "
            "(host syncs, cross-step host recurrences, or raw "
            "host-scalar math in update()); supported: SGD, NAG, "
            "Signum, Adam, Adamax, FTML, Ftrl, RMSProp, AdaGrad, "
            "AdaDelta.  Use the eager Trainer path instead."
            % type(opt).__name__)
    if trainer._update_on_kvstore:
        raise MXNetError(
            "compiled_step: updates run on the kvstore servers "
            "(update_on_kvstore=True) — the update cannot be traced "
            "into a device program; use the eager path")
    kv_type = trainer._kvstore_type
    kv_name = kv_type if isinstance(kv_type, str) \
        else getattr(kv_type, "type", "") or ""
    if "dist" in kv_name:
        raise MXNetError(
            "compiled_step: dist kvstore training is not compiled "
            "(gradients must cross processes); use the eager path "
            "or the sharded parallel/gluon_step.py step")
    if not zero and len(trainer._contexts) > 1:
        raise MXNetError(
            "compiled_step: multi-context (per-device replica) "
            "training is not compiled; use parallel/gluon_step.py "
            "for the sharded whole-step path")


class _Entry:
    """One jitted whole-step program for a fixed input signature."""

    __slots__ = ("fn", "n_state_leaves", "cost", "xray")

    def __init__(self, fn, n_state_leaves):
        self.fn = fn
        self.n_state_leaves = n_state_leaves
        self.cost = None
        self.xray = None


def _state_leaves(st, out):
    """Collect the NDArray leaves of one updater state tree, in the
    deterministic traversal order every phase (flatten, trace rebuild,
    post-call rebind) shares."""
    if st is None:
        return
    if isinstance(st, NDArray):
        out.append(st)
    elif isinstance(st, (tuple, list)):
        for c in st:
            _state_leaves(c, out)
    else:
        raise MXNetError(
            "compiled_step: unsupported optimizer state leaf %r — "
            "states must be (nested tuples/lists of) NDArrays or None"
            % type(st).__name__)


def _rebuild_state(st, it):
    """The same tree with each NDArray leaf replaced by an NDArray
    wrapping the next traced value from ``it``."""
    if st is None:
        return None
    if isinstance(st, NDArray):
        return NDArray(next(it))
    if isinstance(st, tuple):
        return tuple(_rebuild_state(c, it) for c in st)
    return [_rebuild_state(c, it) for c in st]


class CompiledStep:
    """Fused fwd+bwd+update over the mainline Gluon/Trainer stack."""

    def __init__(self, block, loss, trainer):
        import jax  # noqa: F401  (fail early off-jax environments)

        self.block = block
        self.loss_block = loss
        self.trainer = trainer
        opt = trainer._optimizer
        _guard_trainer(trainer)
        params = list(block.collect_params().values())
        self.trainable = [p for p in params if p.grad_req != "null"]
        self.aux = [p for p in params if p.grad_req == "null"]
        if not self.trainable:
            raise MXNetError("compiled_step: block has no trainable "
                             "parameters")
        self._index = {}
        for p in self.trainable:
            i = trainer._param2idx.get(p.name)
            if i is None:
                raise MXNetError(
                    "compiled_step: parameter %r is not managed by this "
                    "Trainer — pass the same collect_params() the "
                    "Trainer was built with" % p.name)
            self._index[p] = i
        ours = {id(p) for p in self.trainable}
        for p in trainer._params:
            if p.grad_req != "null" and id(p) not in ours:
                raise MXNetError(
                    "compiled_step: Trainer parameter %r is not part of "
                    "this block — it would silently stop updating; "
                    "compile the block that owns every trainable "
                    "parameter" % p.name)
        # one slot per (param index, per-step scalar name): the traced
        # arguments the host refills from Optimizer.step_scalars each
        # step.  Discovered once — only the names matter here.
        self._slots = []
        for p in self.trainable:
            i = self._index[p]
            for name in sorted(opt.step_scalars(i)):
                self._slots.append((i, name))
        self._cache = {}
        _LIVE.add(self)

    # ------------------------------------------------------------ build
    def _updater(self):
        return self.trainer._updaters[0]

    def _ensure_states(self):
        """Materialize updater state for every trainable index — what
        ``Updater.__call__`` does lazily on the eager path, done
        eagerly here so the state tree exists before tracing."""
        opt = self.trainer._optimizer
        upd = self._updater()
        for p in self.trainable:
            i = self._index[p]
            if i not in upd.states:
                upd.states[i] = opt.create_state_multi_precision(
                    i, p.data())
                upd.states_synced[i] = True

    def _collect_state(self):
        """``(leaf NDArrays, values)`` for every trainable index, in
        slot order.  Re-collected every step — checkpoint restore may
        rebuild the state tree objects, so cached leaf lists would go
        stale and update orphans."""
        upd = self._updater()
        leaves = []
        for p in self.trainable:
            _state_leaves(upd.states[self._index[p]], leaves)
        return leaves, tuple(nd._data for nd in leaves)

    def _build(self, x_nd, y_nd):
        """Trace + jit one whole-step program for this signature."""
        import jax
        import jax.numpy as jnp

        from .gluon.block import staged_call

        # resolve deferred shapes with one eager warmup forward, like
        # HybridBlock._call_cached does before its staging trace
        from . import autograd as _ag
        from .gluon.parameter import DeferredInitializationError

        try:
            for p in self.block.collect_params().values():
                p._check_initialized()
        except DeferredInitializationError:
            with _ag.pause():
                self.block(x_nd)
            params = list(self.block.collect_params().values())
            self.trainable = [p for p in params if p.grad_req != "null"]
            self.aux = [p for p in params if p.grad_req == "null"]
        self._ensure_states()
        trainable = self.trainable
        aux = self.aux
        block = self.block
        loss_block = self.loss_block
        upd = self._updater()
        indices = [self._index[p] for p in trainable]
        state_trees = [upd.states[i] for i in indices]
        per_tree_leaves = []
        for st in state_trees:
            leaves = []
            _state_leaves(st, leaves)
            per_tree_leaves.append(len(leaves))
        n_leaves = sum(per_tree_leaves)
        slots = list(self._slots)

        def step_fn(pvals, svals, avals, x, y, seed, scalars):
            aux_override = {p: NDArray(v) for p, v in zip(aux, avals)}

            def loss_sum(tv):
                override = {p: NDArray(v)
                            for p, v in zip(trainable, tv)}
                override.update(aux_override)

                def fwd(x_in):
                    out = block(x_in)
                    with _xray.scope(_xray.REGION_LOSS):
                        loss = loss_block(out, NDArray(y))
                    if not isinstance(loss, NDArray):
                        raise MXNetError(
                            "compiled_step: the loss must return one "
                            "NDArray, got %r" % type(loss).__name__)
                    return loss

                loss, scope = staged_call(fwd, override, seed,
                                          (NDArray(x),))
                new_aux = tuple(
                    scope.aux_updates.get(p, aux_override[p]._data)
                    for p in aux)
                # ones-cotangent over the loss output — exactly what
                # eager `l.backward()` seeds, so gradients match the
                # tape bit for bit
                return jnp.sum(loss._data), (loss._data, new_aux)

            # x-ray: the grad wrapper is a direction marker only — the
            # transpose() metadata XLA records inside is what flags
            # backward instructions; canonical_scope filters the marker
            with _xray.scope(_xray.GRAD_MARKER):
                (_, (loss_vec, new_aux)), grads = jax.value_and_grad(
                    loss_sum, has_aux=True)(tuple(pvals))

            # the REAL optimizer update: rebuild each state tree with
            # traced leaves, swap it into the live Updater, and run the
            # same fused-kernel update path the eager Trainer runs —
            # per-step scalars arrive through the feed as traced args
            it = iter(svals)
            traced_states = {i: _rebuild_state(st, it)
                             for i, st in zip(indices, state_trees)}
            feed = {(i, name): scalars[k]
                    for k, (i, name) in enumerate(slots)}
            real_states = upd.states
            new_pvals = []
            try:
                upd.states = traced_states
                with _opt.scalar_feed(feed), \
                        _xray.scope(_xray.REGION_OPT):
                    for j, p in enumerate(trainable):
                        w_nd = NDArray(pvals[j])
                        g_nd = NDArray(grads[j])
                        upd(indices[j], g_nd, w_nd)
                        new_pvals.append(w_nd._data)
            finally:
                upd.states = real_states
            new_svals = []
            for i in indices:
                leaves = []
                _state_leaves(traced_states[i], leaves)
                new_svals.extend(nd._data for nd in leaves)
            return (loss_vec, tuple(new_pvals), tuple(new_svals),
                    tuple(new_aux))

        fn = jax.jit(step_fn, donate_argnums=(0, 1, 2))
        return _Entry(fn, n_leaves)

    def _analyze(self, entry, args):
        """Capture the program's XLA cost/memory analysis at compile
        time (one extra AOT compile, like ``Op.analyze_entry`` — only
        when cost capture is active)."""
        if not _registry.cost_capture_active():
            return
        import time as _time

        import jax

        t0 = _time.perf_counter()
        try:
            specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
                if hasattr(a, "shape") else a, args)
            compiled = entry.fn.lower(*specs).compile()
            entry.cost = _registry.compiled_cost(compiled)
            entry.xray = _xray.analyze(compiled, cost=entry.cost)
        except Exception:  # analysis must never break the step
            entry.cost = None
        _rts.inc("cost_analysis_entries" if entry.cost
                 else "cost_analysis_failures")
        if entry.xray:
            _rts.inc("xray_programs")
        _rts.inc("cost_analysis_seconds", _time.perf_counter() - t0)

    # ------------------------------------------------------------- step
    def step(self, x, y):
        """One fused training step; returns the loss output (async).

        Runs under the SAME per-step instrumentation as the eager
        ``Trainer.step`` (``gluon.trainer._StepTelemetry``: trainer:step
        span + step-wall histogram, health step clock + crash dump,
        device-memory counter event, auto-checkpoint hook — pinned,
        because the next call donates the captured buffers — stepstats
        window close, metrics-timeline sample), so every later
        observability layer extends both paths in one place."""
        from .gluon.trainer import _StepTelemetry

        _rts.inc("trainer_steps")
        _rts.inc("compiled_step_steps")
        hm = _health.monitor() if _health._state["on"] else None
        batch_size = int(x.shape[0]) if hasattr(x, "shape") else None
        with _StepTelemetry(self.trainer, batch_size, hm, compiled=True):
            return self._step_impl(x, y)

    def _step_impl(self, x, y):
        x_nd = x if isinstance(x, NDArray) else NDArray(_as_jax(x))
        y_nd = y if isinstance(y, NDArray) else NDArray(_as_jax(y))
        trainer = self.trainer
        opt = trainer._optimizer
        batch = int(x_nd.shape[0])
        # same rescale contract as Trainer._step: scale/batch, resolved
        # before the update reads it (and baked per cache entry — the
        # key carries it, so a batch/scale change builds a new program)
        opt.rescale_grad = trainer._scale / batch
        key = (tuple(x_nd.shape), str(x_nd.dtype),
               tuple(y_nd.shape), str(y_nd.dtype),
               float(opt.rescale_grad))
        entry = self._cache.get(key)
        hit = entry is not None
        timed = _prof._state["running"] or _rts.DIAG_TIMING
        t0 = _prof._now_us() if (timed or not hit) else 0

        if not hit:
            _rts.record_dispatch("compiled_step", "miss")
            _rts.record_compile_key("compiled_step", key)
            entry = self._build(x_nd, y_nd)
            self._cache[key] = entry
        else:
            _rts.record_dispatch("compiled_step", "hit")

        # advance the optimizer's host step counters (the eager path
        # does this inside update(); the feed suppresses it in-trace),
        # then refill the per-step scalar slots with fresh host values
        table = {}
        for p in self.trainable:
            i = self._index[p]
            opt._update_count(i)
            table[i] = opt.step_scalars(i)
        scalars = tuple(float(table[i][name]) for i, name in self._slots)
        seed = _random.next_key()

        leaves, svals = self._collect_state()
        if len(leaves) != entry.n_state_leaves:
            raise MXNetError(
                "compiled_step: optimizer state changed structure "
                "(%d leaves vs %d at trace time) — rebuild the "
                "CompiledStep after swapping optimizers"
                % (len(leaves), entry.n_state_leaves))
        pvals = tuple(p.data()._data for p in self.trainable)
        avals = tuple(p.data()._data for p in self.aux)
        args = (pvals, svals, avals, x_nd._data, y_nd._data, seed,
                scalars)
        # latched at the point buffers are actually handed to a donating
        # call (a failed build/guard above never donated anything, and
        # must not force pinned checkpoints process-wide)
        _state["donating"] = True
        loss_v, new_p, new_s, new_aux = entry.fn(*args)

        # rebind: the donated inputs are gone; the same NDArray objects
        # now carry the updated buffers, so checkpointing/health/eager
        # interop keep working with zero copies
        for p, v in zip(self.trainable, new_p):
            p._data[0]._assign(v)
        for nd, v in zip(leaves, new_s):
            nd._assign(v)
        for p, v in zip(self.aux, new_aux):
            p._data[0]._assign(v)

        dur = (_prof._now_us() - t0) if (timed or not hit) else 0
        if not hit:
            _rts.add_compile_seconds("compiled_step", dur / 1e6)
            # AOT cost/memory capture AFTER the timed window (the
            # registry convention: analysis wall-time has its own
            # counter); donated args still expose shape/dtype metadata
            self._analyze(entry, args)
        elif timed:
            _rts.add_compiled_step_seconds(dur / 1e6)
        if _prof._state["running"]:
            ev = {"op": "compiled_step",
                  "cache": "hit" if hit else "miss"}
            if not hit:
                ev["compile_ms"] = round(dur / 1e3, 3)
            _prof.add_event("dispatch:compiled_step", "operator", "X",
                            ts=t0, dur=dur, args=ev)
        return NDArray(loss_v, x_nd._ctx)


class ZeroCompiledStep:
    """``trainer.compile(block, loss, zero=True)``: the whole-step
    program with ZeRO weight-update sharding (the
    parallel/gluon_step.py zero path) behind the same ``step()`` /
    telemetry contract as :class:`CompiledStep`.

    Differences from the replicated CompiledStep:

    - **Functional state**: params and optimizer state live as flat
      1/n 'dp' shards inside the wrapped ``GluonTrainStep``, not in the
      Gluon Parameters.  ``sync_to_params()`` writes them back, and
      runs automatically on the step right before an auto-checkpoint
      interval boundary so the captured parameter snapshot is fresh
      (optimizer state in that snapshot is the sharded run's business:
      use ``save_zero``/``restore_zero`` — the sharded checkpoint —
      for a complete resumable unit, docs/ZERO.md).
    - ``step()`` returns the mean loss (a scalar NDArray), not the
      per-sample loss vector: the sharded step reduces the loss inside
      the program.
    - ``rescale_grad`` semantics: gradients leave the backward as
      mean-of-batch (the sharded step differentiates the mean loss),
      so the optimizer's effective rescale is ``trainer._scale`` — set
      at build time and baked into the program; changing the scale
      afterwards requires a rebuild and raises.
    """

    def __init__(self, block, loss, trainer, mesh=None):
        from .parallel.gluon_step import GluonTrainStep

        self.block = block
        self.loss_block = loss
        self.trainer = trainer
        _guard_trainer(trainer, zero=True)
        opt = trainer._optimizer
        self._scale = float(trainer._scale)
        opt.rescale_grad = self._scale
        self._gstep = GluonTrainStep(block, loss, mesh=mesh, zero=True,
                                     optimizer=opt)
        self.zero_layout = self._gstep.zero_layout
        self._cache = {}
        _LIVE.add(self)

    # -------------------------------------------------------- interop
    def sync_to_params(self):
        """Gather the sharded functional params off the mesh back into
        the Gluon Parameters (checkpoint/eager-eval interop)."""
        self._gstep.sync_to_params()

    def save_zero(self, step, mgr=None):
        return self._gstep.save_zero(step, mgr=mgr)

    def restore_zero(self, manifest, mgr=None):
        return self._gstep.restore_zero(manifest, mgr=mgr)

    # ------------------------------------------------------------- step
    def step(self, x, y):
        """One fused ZeRO training step; returns the mean loss (async).
        Same per-step instrumentation as ``CompiledStep.step`` (see
        its docstring) plus the ``zero_*`` collective-bytes counters
        the wrapped sharded step emits."""
        from .gluon.trainer import _StepTelemetry

        _rts.inc("trainer_steps")
        _rts.inc("compiled_step_steps")
        hm = _health.monitor() if _health._state["on"] else None
        batch_size = int(x.shape[0]) if hasattr(x, "shape") else None
        with _StepTelemetry(self.trainer, batch_size, hm, compiled=True):
            return self._step_impl(x, y)

    def _step_impl(self, x, y):
        import numpy as np

        if float(self.trainer._scale) != self._scale:
            raise MXNetError(
                "zero compiled step: the loss scale changed (%s -> %s) "
                "after the program baked it — rebuild with "
                "trainer.compile(..., zero=True)"
                % (self._scale, self.trainer._scale))
        xv = getattr(x, "_data", x)
        yv = getattr(y, "_data", y)
        xq, yq = self._gstep.put_batch(np.asarray(xv), np.asarray(yv))
        key = (tuple(xq.shape), str(xq.dtype),
               tuple(yq.shape), str(yq.dtype))
        entry = self._cache.get(key)
        hit = entry is not None
        timed = _prof._state["running"] or _rts.DIAG_TIMING
        t0 = _prof._now_us() if (timed or not hit) else 0
        if not hit:
            _rts.record_dispatch("compiled_step", "miss")
            _rts.record_compile_key("compiled_step", key)
            entry = _Entry(self._gstep._step, 0)
            self._cache[key] = entry
        else:
            _rts.record_dispatch("compiled_step", "hit")

        loss = self._gstep(xq, yq)

        dur = (_prof._now_us() - t0) if (timed or not hit) else 0
        if not hit:
            _rts.add_compile_seconds("compiled_step", dur / 1e6)
            self._analyze(entry, (xq, yq))
        elif timed:
            _rts.add_compiled_step_seconds(dur / 1e6)
        if _prof._state["running"]:
            ev = {"op": "compiled_step", "zero": True,
                  "cache": "hit" if hit else "miss"}
            if not hit:
                ev["compile_ms"] = round(dur / 1e3, 3)
            _prof.add_event("dispatch:compiled_step", "operator", "X",
                            ts=t0, dur=dur, args=ev)

        # auto-checkpoint fires in _StepTelemetry.__exit__ when this
        # step crosses the interval boundary — the Gluon Parameters
        # must carry THIS step's values by then (the functional shards
        # are the source of truth otherwise)
        from . import checkpoint as _ckpt

        mgr = _ckpt.manager()
        if mgr is not None and mgr.interval \
                and (mgr.step_clock + 1) % mgr.interval == 0:
            self._gstep.sync_to_params()
        return NDArray(loss)

    def _analyze(self, entry, batch):
        """AOT cost/memory capture of the sharded program (the
        CompiledStep._analyze convention) — feeds the diag-dump cost
        section the perfdoctor zero rule reads."""
        if not _registry.cost_capture_active():
            return
        import time as _time

        import jax

        g = self._gstep
        t0 = _time.perf_counter()
        try:
            def spec(a):
                return jax.ShapeDtypeStruct(a.shape, a.dtype)

            key = jax.random.PRNGKey(0)  # shape/dtype stand-in only
            args = [tuple(spec(v) for v in g.train_vals),
                    tuple(spec(v) for v in g.opt_state),
                    tuple(spec(v) for v in g.aux_vals),
                    spec(batch[0]), spec(batch[1]), spec(key)]
            if g._opt_update is not None:
                args.append(tuple(0.0 for _ in g._opt_update.slots))
            compiled = g._step.lower(*args).compile()
            entry.cost = _registry.compiled_cost(compiled)
            entry.xray = _xray.analyze(compiled, cost=entry.cost,
                                       label="zero_step", zero=True)
        except Exception:  # analysis must never break the step
            entry.cost = None
        _rts.inc("cost_analysis_entries" if entry.cost
                 else "cost_analysis_failures")
        if entry.xray:
            _rts.inc("xray_programs")
        _rts.inc("cost_analysis_seconds", _time.perf_counter() - t0)


def _as_jax(a):
    import jax.numpy as jnp

    return jnp.asarray(a)


def cost_snapshot():
    """Read-side aggregate over every live CompiledStep's program
    cache, shaped like ``ops.registry.cost_snapshot`` rows so the diag
    dump / report cost section renders it like any per-op jit entry."""
    entries = []
    for cs in list(_LIVE):
        entries.extend(list(cs._cache.values()))
    if not entries:
        return {}
    analyzed = [e.cost for e in entries if e.cost]
    rec = {"cache_entries": len(entries), "analyzed": len(analyzed)}
    for k, dst in (("flops", "flops_per_call"),
                   ("bytes_accessed", "bytes_per_call")):
        vals = [c[k] for c in analyzed if k in c]
        if vals:
            rec[dst] = sum(vals) / len(vals)
    for k in ("output_bytes", "temp_bytes", "argument_bytes"):
        vals = [c[k] for c in analyzed if k in c]
        if vals:
            rec[k] = int(sum(vals))
    return {"compiled_step": rec}


def xray_snapshot():
    """Read-side aggregate of every live program's x-ray table (the
    cost_snapshot convention): ``{"programs": [table, ...]}`` ordered
    oldest→newest by capture sequence, ``{}`` when nothing was
    captured.  runtime_stats.snapshot merges this as its ``xray``
    section; the report/diagnose renderers and the perfdoctor rules
    read the newest table per program label."""
    programs = []
    for cs in list(_LIVE):
        for e in list(cs._cache.values()):
            t = getattr(e, "xray", None)
            if t:
                programs.append(t)
    if not programs:
        return {}
    programs.sort(key=lambda t: t.get("seq", 0))
    return {"programs": programs}
