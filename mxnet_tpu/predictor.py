"""Lightweight prediction-only API (deployment surface).

Reference: `include/mxnet/c_predict_api.h` (MXPredCreate/SetInput/Forward/
GetOutput/Reshape) and its Python wrapper `amalgamation/python/
mxnet_predict.py` (class Predictor, load_ndarray_file), exercised by
`tests/python/unittest/test_predictor.py`.

TPU-native form: the predictor binds an exported Symbol (JSON) plus its
saved parameters and stages the forward pass through the normal XLA jit
path — there is no separate stripped-down inference engine to maintain,
XLA *is* the deployment runtime.  The same surface is exported over the
C ABI for non-Python consumers in `native/src/predict.cc`
(MXTPUPred* — see cpp-package/ for the C++ RAII wrapper).
"""

from __future__ import annotations

import io
import time

import numpy as np

from . import histogram as _histogram
from . import profiler as _profiler
from . import runtime_stats as _rts

__all__ = ["Predictor", "load_ndarray_file"]


def load_ndarray_file(nd_bytes):
    """Deserialize an `mx.nd.save` blob (bytes) to numpy arrays.

    Returns a dict (name → array) when the blob was saved from a dict,
    else a list.  Reference: MXNDListCreate in c_predict_api.h /
    load_ndarray_file in amalgamation/python/mxnet_predict.py.
    """
    from .ndarray.ndarray import _parse_npz

    data = np.load(io.BytesIO(bytes(nd_bytes)), allow_pickle=False)
    _fmt, parsed = _parse_npz(data)
    return parsed


class Predictor:
    """Runs forward passes over an exported model.

    Parameters
    ----------
    symbol_json_str : str
        Contents of the ``*-symbol.json`` file (NOT a path).
    param_raw_bytes : bytes
        Contents of the ``*.params`` file ("arg:name"/"aux:name" keys).
    input_shapes : dict of str to tuple
        Shapes of the input variables.
    dev_type : str, optional
        "cpu" or "tpu" ("gpu" accepted as an alias of "tpu").
    dev_id : int, optional
    type_dict : dict of str to dtype, optional
        Input dtypes (default float32).
    """

    def __init__(self, symbol_json_str, param_raw_bytes, input_shapes,
                 dev_type="cpu", dev_id=0, type_dict=None):
        from . import context as _context
        from . import ndarray as _nd
        from . import symbol as _symbol

        self._symbol = _symbol.load_json(symbol_json_str)
        self._symbol_json = symbol_json_str
        self._dev_type, self._dev_id = dev_type, dev_id
        self._type_dict = dict(type_dict or {})
        if dev_type in ("tpu", "gpu"):
            self._ctx = _context.tpu(dev_id)
        else:
            self._ctx = _context.cpu(dev_id)

        params = load_ndarray_file(param_raw_bytes)
        if not isinstance(params, dict):
            raise ValueError("params blob must be a dict of arg:/aux: keys")
        # parsed once; reshape() rebinds from these device arrays without
        # touching the serialized blob again (reference: MXPredReshape
        # shares weights with the source predictor)
        self._arg_params = {k[4:]: _nd.array(v, ctx=self._ctx, dtype=v.dtype)
                            for k, v in params.items()
                            if k.startswith("arg:")}
        self._aux_params = {k[4:]: _nd.array(v, ctx=self._ctx, dtype=v.dtype)
                            for k, v in params.items()
                            if k.startswith("aux:")}
        self._bind(input_shapes)

    def _bind(self, input_shapes):
        if not isinstance(input_shapes, dict):
            raise ValueError("Expect input_shapes to be dict str->tuple")
        for v in input_shapes.values():
            if not isinstance(v, tuple):
                raise ValueError("Expect input_shapes to be dict str->tuple")
        arg_names = set(self._symbol.list_arguments())
        unknown = set(input_shapes) - arg_names
        if unknown:
            raise ValueError("input_shapes names %s not in symbol arguments"
                             % sorted(unknown))
        self._input_names = sorted(input_shapes)
        self._exec = self._symbol.simple_bind(
            ctx=self._ctx, grad_req="null", type_dict=self._type_dict,
            **input_shapes)
        self._exec.copy_params_from(self._arg_params, self._aux_params,
                                    allow_extra_params=True)
        # output shapes are fixed by the bound input shapes; computed once
        # (get_output_shape sits on the C ABI per-inference path)
        _, out_shapes, _ = self._symbol.infer_shape(**input_shapes)
        self._out_shapes = [tuple(s) for s in out_shapes]
        self._inputs = {}
        self._outputs = None

    # ------------------------------------------------------------ running
    def forward(self, **kwargs):
        """Run forward with named inputs (numpy arrays); then
        ``get_output(i)``.

        Telemetry seam (the ``Trainer.step`` convention): the forward
        rides a ``predictor:forward`` profiler span, lands in the
        ``predictor:forward`` latency histogram (guard-first — one dict
        read when collection is off), and bumps the always-on
        ``predictor_forwards`` counter, so legacy predictor and serving
        runs show up in diag dumps / ``--compare`` like training
        steps do.  The executor underneath feeds the ``forward``
        stepstats phase as usual."""
        hist_on = _histogram._state["on"]
        if hist_on:
            t0 = time.perf_counter()
        with _profiler.span("predictor:forward", "predictor"):
            self._forward_impl(**kwargs)
        _rts.inc("predictor_forwards")
        if hist_on:
            _histogram.observe("predictor:forward",
                               time.perf_counter() - t0)
        return self

    def _forward_impl(self, **kwargs):
        for k, v in kwargs.items():
            if not isinstance(v, np.ndarray):
                raise ValueError("Expect numpy ndarray as input")
            if k not in self._input_names:
                raise ValueError("unknown input '%s' (expected %s)"
                                 % (k, self._input_names))
            dt = np.dtype(self._type_dict.get(k, np.float32))
            expect = tuple(self._exec.arg_dict[k].shape)
            v = np.asarray(v, dtype=dt, order="C")
            if tuple(v.shape) != expect:
                raise ValueError("input '%s' shape %s != bound shape %s "
                                 "(use reshape())" % (k, v.shape, expect))
            self._inputs[k] = v
        self._outputs = self._exec.forward(is_train=False, **self._inputs)

    def get_output(self, index):
        """The index-th output as a numpy array."""
        if self._outputs is None:
            raise RuntimeError("call forward() before get_output()")
        return self._outputs[index].asnumpy()

    @property
    def num_outputs(self):
        return len(self._symbol)

    def get_output_shape(self, index):
        return self._out_shapes[index]

    def get_input_names(self):
        return list(self._input_names)

    # ------------------------------------------------------------ reshape
    def reshape(self, input_shapes):
        """Rebind with new input shapes, sharing the already-loaded
        weights (reference: MXPredReshape; here the jit cache keys on the
        new signature)."""
        self._bind(input_shapes)
        return self

    def _reshape_clone(self, input_shapes):
        """New predictor over the same weight arrays (the C ABI's
        MXTPUPredReshape returns a fresh handle)."""
        new = Predictor.__new__(Predictor)
        new._symbol = self._symbol
        new._symbol_json = self._symbol_json
        new._dev_type, new._dev_id = self._dev_type, self._dev_id
        new._type_dict = dict(self._type_dict)
        new._ctx = self._ctx
        new._arg_params = self._arg_params
        new._aux_params = self._aux_params
        new._bind(input_shapes)
        return new
