"""RecordIO (reference: python/mxnet/recordio.py + dmlc-core recordio).

Same binary format concept: magic + length-prefixed records with
continuation handling omitted (single-part records), plus the IRHeader
image packing used by im2rec/ImageRecordIter.  A C++ fast path for bulk
sequential reads lives in mxnet_tpu/native/ (used when built).
"""

from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as _np

_MAGIC = 0xCED7230A

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential record file reader/writer (reference: recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.writable = flag == "w"
        self.is_open = False
        self.open()

    def open(self):
        self.handle = open(self.uri, "wb" if self.writable else "rb")
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        if not self.writable:
            d["_pos"] = self.handle.tell() if self.is_open else 0
        return d

    def __setstate__(self, d):
        pos = d.pop("_pos", 0)
        self.__dict__.update(d)
        self.open()
        if not self.writable:
            self.handle.seek(pos)

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        self.handle.write(struct.pack("<II", _MAGIC, len(buf)))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        head = self.handle.read(8)
        if not head:
            return None  # clean EOF at a record boundary
        if len(head) < 8:
            # a partial header is file corruption, not EOF — surfacing
            # it beats silently dropping the tail of a dataset
            raise IOError("truncated RecordIO header in %s (%d trailing "
                          "bytes)" % (self.uri, len(head)))
        magic, length = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise IOError("invalid RecordIO magic in %s" % self.uri)
        buf = self.handle.read(length)
        if len(buf) < length:
            raise IOError(
                "truncated RecordIO payload in %s (record wants %d "
                "bytes, file has %d)" % (self.uri, length, len(buf)))
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return buf

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Indexed record file (reference: recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.exists(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        if self.writable:
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None and not self.fidx.closed:
            self.fidx.close()
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack a string payload with IRHeader (reference: recordio.pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id, header.id2)
        return hdr + s
    label = _np.asarray(header.label, dtype=_np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Unpack IRHeader + payload (reference: recordio.unpack)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (HWC uint8) as header + encoded image bytes —
    the reference's wire format (recordio.pack_img), so records
    interoperate with reference-built .rec files and `unpack` output
    feeds `image.imdecode` directly.  Raw-tagged fallback only when no
    encoder is available."""
    try:
        from io import BytesIO

        from PIL import Image

        buff = BytesIO()
        fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
        Image.fromarray(img).save(buff, format=fmt, quality=quality)
        return pack(header, buff.getvalue())
    except ImportError:
        arr = _np.ascontiguousarray(img, dtype=_np.uint8)
        meta = struct.pack("<III", *((arr.shape + (1, 1, 1))[:3]))
        return pack(header, b"RAW0" + meta + arr.tobytes())


def unpack_img(s, iscolor=-1):
    """Unpack to (IRHeader, HWC uint8 array)."""
    header, payload = unpack(s)
    tag = payload[:4]
    if tag == b"RAW0":
        h, w, c = struct.unpack("<III", payload[4:16])
        img = _np.frombuffer(payload[16:16 + h * w * c], dtype=_np.uint8)
        img = img.reshape((h, w, c) if c > 1 else (h, w))
    else:
        # encoded image bytes (JPEG/PNG), the reference wire format;
        # "IMG0"-tagged records from early versions of this framework
        # are also accepted
        from io import BytesIO

        from PIL import Image

        if tag == b"IMG0":
            payload = payload[4:]
        img = _np.asarray(Image.open(BytesIO(payload)))
    return header, img
