"""Continuous-batching multi-tenant inference server over the predictor
stack.

The deployment surface so far (``predictor.py``, ``native/src/
predict.cc``) runs one request at a time: no concurrency, no batching,
no latency accounting — fine for an offline scorer, useless for the
millions-of-users north star.  This module is the serving layer:

- an :class:`InferenceServer` wraps a loaded model (a
  :class:`~mxnet_tpu.predictor.Predictor`, a hybridized Gluon block, or
  a pure callable) behind a thread-safe request queue;
- a batcher thread packs concurrent requests into **bucketed batch
  shapes** (a configurable ladder, default 1/2/4/8/16, padded to the
  bucket with the padded rows masked out of the scatter) — the
  reference's ``BucketingModule`` idiom applied to serving: ONE cached
  jitted executable per bucket, built lazily on first use and counted
  (``serve_bucket_compiles``), so shape churn is always an explicit
  jit-cache miss and never a silent retrace (XLA whole-program fusion
  economics, arXiv:2301.13062);
- a small worker pool pipelines host→device staging, device compute,
  and device→host result scatter, so on an async backend the device
  never idles behind host copies (the scatter's ``device_get`` is the
  module's ONE deliberate host-sync sink, pragma'd at the source per
  the mxlint callgraph rule);
- every batch feeds the operational substrate: per-request queue-wait
  and end-to-end latency into ``histogram.py`` (``serve:queue_wait``,
  ``serve:e2e``, ``serve:batch`` plus per-bucket ``serve:batch:b<B>``),
  request/sample/byte/occupancy counters into ``runtime_stats``
  (scrapeable live through the PR 10 Prometheus endpoint), an optional
  JSONL timeline of per-batch samples (``MXNET_TPU_SERVE_METRICS``,
  ``log.rank_suffix_path`` honored) shaped like ``metrics_timeline``
  samples so the perf-doctor trend rules run over a serving soak
  unchanged, and health-layer NaN/Inf sentinels on served outputs —
  a non-finite row is a rate-limited warning + a rejected response +
  a flight record, never a silent bad payload;
- :meth:`InferenceServer.stop` drains the queue before the workers
  exit, so shutdown never drops an accepted request;
- request-grain observability rides the same seams guard-first: the
  ``reqtrace`` lifecycle ring (tail-sampled per-request records +
  chrome-trace flow events) and the ``slo`` error-budget counters —
  one dict read per seam when disabled (docs/OBSERVABILITY.md
  "Request x-ray & SLOs").

Bench: ``tools/loadgen.py`` (open-loop Poisson arrivals, p50/p99/p99.9
vs offered QPS, serial-`Predictor.forward` baseline) — also reachable
as ``python bench.py --serve``.  Doctor rules: ``perfdoctor``'s
``serve-queue-dominated`` / ``serve-bucket-churn``; section rendering:
``tools/diagnose.py --serving``.  Docs: docs/SERVING.md.

Environment variables
---------------------
``MXNET_TPU_SERVE_BUCKETS``   comma bucket ladder (default
    ``1,2,4,8,16``); the largest bucket is the max batch.
``MXNET_TPU_SERVE_QUEUE``     max queued samples before submissions are
    rejected with :class:`RequestRejected` (default 1024) — explicit
    backpressure instead of unbounded latency.
``MXNET_TPU_SERVE_WAIT_MS``   max milliseconds a partial batch waits
    for more requests while every worker is busy (default 2.0; with an
    idle worker a partial batch dispatches immediately, so an unloaded
    server adds no batching latency).
``MXNET_TPU_SERVE_WORKERS``   pipeline worker threads (default 2).
``MXNET_TPU_SERVE_METRICS``   JSONL path for per-batch timeline
    samples (rank-suffixed via ``log.rank_suffix_path``).
``MXNET_TPU_SERVE_SENTINEL``  ``0`` disables the served-output NaN/Inf
    sentinel (default on).
``MXNET_TPU_SERVE_WARN_INTERVAL``  min seconds between non-finite
    rejection warnings (default 60).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

import numpy as np

from . import autopilot as _autopilot
from . import device_memory as _dm
from . import health as _health
from . import histogram as _histogram
from . import reqtrace as _reqtrace
from . import runtime_stats as _rts
from . import slo as _slo
from .log import get_logger, rank_suffix_path, warn_rate_limited

__all__ = ["InferenceServer", "RequestRejected", "ServerStopped",
           "DEFAULT_BUCKETS", "snapshot", "servers"]

DEFAULT_BUCKETS = (1, 2, 4, 8, 16)

WARN_INTERVAL = float(os.environ.get(
    "MXNET_TPU_SERVE_WARN_INTERVAL", "60"))

_logger_cache: list = []


def _logger():
    if not _logger_cache:
        _logger_cache.append(get_logger("mxnet_tpu.serving"))
    return _logger_cache[0]


class RequestRejected(RuntimeError):
    """The server refused (queue full, bad shape) or rejected (non-
    finite output) this request — the caller always gets an explicit
    error, never a silent bad payload."""


class ServerStopped(RuntimeError):
    """The server stopped without serving this request (``stop(
    drain=False)``)."""


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_buckets():
    raw = os.environ.get("MXNET_TPU_SERVE_BUCKETS")
    if not raw:
        return DEFAULT_BUCKETS
    try:
        out = tuple(sorted({int(b) for b in raw.split(",") if b.strip()}))
    except ValueError:
        return DEFAULT_BUCKETS
    return out or DEFAULT_BUCKETS


def _fetch(values):
    """Materialize a batch's output device buffers on host.

    THE deliberate host-sync sink of the serving layer: it runs on a
    pipeline worker thread at the scatter stage — after the device
    compute was dispatched — never on a compute path, and the whole
    output list transfers in one batched ``device_get``."""
    import jax

    return jax.device_get(list(values))  # mxlint: disable=trace-host-sync


def _device_put(array):
    """Stage one padded host batch onto the default device (the
    host→device leg of the pipeline; async on real backends)."""
    import jax

    return jax.device_put(array)


# ------------------------------------------------------------- requests


class _Request:
    """One queued inference request: named input arrays with a leading
    sample axis, plus the future the caller waits on.

    The completion event is allocated LAZILY — only a caller that
    blocks in :meth:`result` before the batch lands pays for a
    ``threading.Event``; the ``_done`` flag itself is a plain
    GIL-atomic attribute write, keeping the per-request submit/scatter
    cost low at high request rates."""

    __slots__ = ("inputs", "n", "t_submit", "t_batched", "t_done",
                 "_done", "_event", "_outputs", "_error",
                 # request x-ray (reqtrace.py): id + lifecycle record,
                 # set only while tracing is on — readers use getattr,
                 # so the disabled path never touches these slots
                 "rid", "trace")

    def __init__(self, inputs, n):
        self.inputs = inputs
        self.n = n
        self.t_submit = time.perf_counter()
        self.t_batched = None
        self.t_done = None
        self._done = False
        self._event = None
        self._outputs = None
        self._error = None

    # -------------------------------------------------------- future API
    def done(self):
        return self._done

    def result(self, timeout=None):
        """Block until served; returns the list of per-output numpy
        arrays (leading axis = this request's sample count).  Raises
        :class:`RequestRejected` / :class:`ServerStopped` on
        rejection."""
        if not self._done:
            ev = self._event
            if ev is None:
                ev = self._event = threading.Event()
            # re-check after publishing the event: a completion that
            # raced the allocation set _done first, then (at worst)
            # missed an event created after its set — the re-check
            # plus the bounded waits below make that race benign
            deadline = None if timeout is None \
                else time.perf_counter() + timeout
            while not self._done:
                if deadline is None:
                    ev.wait(0.5)
                elif not ev.wait(min(0.5, deadline -
                                     time.perf_counter())) \
                        and time.perf_counter() >= deadline:
                    raise TimeoutError(
                        "inference request not served within %.3fs"
                        % timeout)
        if self._error is not None:
            raise self._error
        return self._outputs

    def _finish(self):
        self.t_done = time.perf_counter()
        self._done = True
        ev = self._event
        if ev is not None:
            ev.set()

    def _complete(self, outputs):
        self._outputs = outputs
        self._finish()

    def _fail(self, error):
        self._error = error
        self._finish()


# --------------------------------------------------------- model adapters


class _PredictorModel:
    """Bucket executables over a loaded :class:`Predictor`: one
    weight-sharing ``_reshape_clone`` per bucket whose executor forward
    is called as a pure jitted function (thread-safe — no shared
    executor state is mutated per call)."""

    def __init__(self, predictor):
        self._pred = predictor
        self.input_names = list(predictor.get_input_names())
        exec_args = predictor._exec.arg_dict
        self.sample_shapes = {n: tuple(exec_args[n].shape[1:])
                              for n in self.input_names}
        self.dtypes = {n: np.dtype(predictor._type_dict.get(n, np.float32))
                       for n in self.input_names}

    def build(self, bucket):
        shapes = {n: (bucket,) + self.sample_shapes[n]
                  for n in self.input_names}
        clone = self._pred._reshape_clone(shapes)
        exc = clone._exec
        fwd, _bwd, _diff = exc._get_fns(False)
        arg_names = exc._arg_names
        base_args = [a._data for a in exc.arg_arrays]
        aux_vals = [a._data for a in exc.aux_arrays]
        input_idx = {n: arg_names.index(n) for n in self.input_names}

        def run(inputs):
            args = list(base_args)
            for name, val in inputs.items():
                args[input_idx[name]] = val
            outs, _new_aux = fwd(args, aux_vals, 0)
            return list(outs)

        return run


class _BlockModel:
    """Bucket executables over a (hybridized) Gluon block with one
    input.  The block call mutates shared cached-graph state, so calls
    are serialized under one lock; each bucket shape jit-caches its own
    executable inside the block's cached graph."""

    def __init__(self, block, sample_shape, input_name="data",
                 dtype=np.float32):
        self._block = block
        self._lock = threading.Lock()
        self.input_names = [input_name]
        self.sample_shapes = {input_name: tuple(sample_shape)}
        self.dtypes = {input_name: np.dtype(dtype)}

    def build(self, bucket):
        from .ndarray import NDArray

        name = self.input_names[0]

        def run(inputs):
            with self._lock:
                out = self._block(NDArray(inputs[name]))
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o._data for o in outs]

        return run


class _CallableModel:
    """Bucket executables over a user callable ``fn(inputs, bucket) ->
    output(s)`` (jax arrays in, jax/numpy arrays out) — the test /
    custom-runtime seam."""

    def __init__(self, fn, input_shapes, dtypes=None):
        self._fn = fn
        self.input_names = list(input_shapes)
        self.sample_shapes = {n: tuple(s) for n, s in input_shapes.items()}
        self.dtypes = {n: np.dtype((dtypes or {}).get(n, np.float32))
                       for n in self.input_names}

    def build(self, bucket):
        fn = self._fn

        def run(inputs):
            out = fn(inputs, bucket)
            return list(out) if isinstance(out, (list, tuple)) else [out]

        return run


def _adapt(model, input_shapes=None, input_name="data", dtype=np.float32):
    from .predictor import Predictor

    if isinstance(model, Predictor):
        return _PredictorModel(model)
    if callable(model) and not hasattr(model, "register_forward_hook"):
        if not input_shapes:
            raise ValueError("a callable model needs input_shapes "
                             "({name: per-sample shape})")
        return _CallableModel(model, input_shapes)
    # Gluon block
    if not input_shapes:
        raise ValueError("a block model needs input_shapes "
                         "({name: per-sample shape})")
    if len(input_shapes) != 1:
        raise ValueError("block serving supports exactly one input")
    (name, shape), = input_shapes.items()
    return _BlockModel(model, shape, input_name=name, dtype=dtype)


# --------------------------------------------------------------- server


# LIVE servers, newest last.  A stopped server is removed (a long-
# lived process re-creating servers must not leak models and compiled
# bucket executables through this registry) and leaves its final stats
# snapshot in _FINAL, so diag dumps of a finished load run still carry
# the serving section without pinning the server object.
_SERVERS: list = []
_FINAL: list = []


class InferenceServer:
    """Continuous-batching inference server over a loaded model.

    Parameters
    ----------
    model : Predictor | gluon.Block | callable
        The loaded model.  A ``Predictor`` brings its own input
        names/shapes; a block or callable needs ``input_shapes``
        (``{name: per-sample shape}``, no batch axis).
    buckets : tuple of int, optional
        Batch-size ladder (default ``MXNET_TPU_SERVE_BUCKETS`` or
        1/2/4/8/16).  The largest bucket caps a single request's
        sample count.
    max_wait_ms / max_queue / workers : optional
        Batch-formation wait, queued-sample bound, and pipeline worker
        count — each defaulting from its ``MXNET_TPU_SERVE_*`` env row.
    metrics_path : str, optional
        JSONL destination for per-batch timeline samples (default
        ``MXNET_TPU_SERVE_METRICS``).

    Use as a context manager (``with InferenceServer(pred) as srv:``)
    or call :meth:`start` / :meth:`stop` explicitly.
    """

    def __init__(self, model, input_shapes=None, buckets=None,
                 max_wait_ms=None, max_queue=None, workers=None,
                 metrics_path=None, name="serve"):
        self._model = _adapt(model, input_shapes=input_shapes)
        self.buckets = tuple(sorted(set(buckets or _env_buckets())))
        if not self.buckets or any(b <= 0 for b in self.buckets):
            raise ValueError("buckets must be positive ints")
        self.max_bucket = self.buckets[-1]
        self.max_wait = (_env_float("MXNET_TPU_SERVE_WAIT_MS", 2.0)
                         if max_wait_ms is None else float(max_wait_ms)) \
            / 1e3
        self.max_queue = _env_int("MXNET_TPU_SERVE_QUEUE", 1024) \
            if max_queue is None else int(max_queue)
        self.num_workers = max(1, _env_int("MXNET_TPU_SERVE_WORKERS", 2)
                               if workers is None else int(workers))
        self.name = name
        self._sentinel_on = os.environ.get(
            "MXNET_TPU_SERVE_SENTINEL") != "0"
        self._metrics_path = metrics_path \
            if metrics_path is not None \
            else os.environ.get("MXNET_TPU_SERVE_METRICS")
        self._metrics_file = None
        self._metrics_lock = threading.Lock()

        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        # mxlint: disable=thread-shared-state -- mutated under _cond; the one lock-free read is a monitoring gauge (staleness harmless)
        self._queued_samples = 0
        self._inflight = 0
        # mxlint: disable=thread-shared-state -- monotonic publication flag: set without the lock, loops re-check it under their condition
        self._stopping = False
        self._running = False
        # mxlint: disable=thread-shared-state -- written in start() before the workers it names exist (Thread.start happens-before)
        self._threads: list = []
        # mxlint: disable=thread-shared-state -- mutated under _batch_cond; the batcher's emptiness peek under _cond is advisory pacing
        self._batchq: collections.deque = collections.deque()
        self._batch_cond = threading.Condition()

        # mxlint: disable=thread-shared-state -- double-checked build cache: lock-free dict get fast path, builds serialized under _bucket_lock
        self._bucket_fns: dict = {}
        self._bucket_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.stats = {"requests": 0, "samples": 0, "batches": 0,
                      "padded_rows": 0, "rejected_queue": 0,
                      "rejected_nonfinite": 0, "rejected_shape": 0,
                      "completed": 0, "errors": 0,
                      "bucket_compiles": 0, "knob_adjusts": 0,
                      "per_bucket": {b: {"batches": 0, "samples": 0}
                                     for b in self.buckets},
                      "first_batch_t": None, "last_batch_t": None}
        self._rejections: collections.deque = collections.deque(maxlen=64)
        # runtime knob-adjust audit trail (set_workers/set_max_wait_ms/
        # set_max_queue); mutated under _stats_lock
        self._adjustments: collections.deque = collections.deque(
            maxlen=32)
        # live worker-thread count, mutated under _batch_cond: grown by
        # set_workers spawning, shrunk by idle workers retiring when it
        # exceeds num_workers
        self._worker_count = 0
        self._batch_seq = 0
        # serving is an observability-first surface: latency percentiles
        # ARE the product, so raise the histogram layer unless the env
        # explicitly forces it off (the metrics_timeline convention)
        if os.environ.get("MXNET_TPU_HISTOGRAMS") != "0":
            _histogram.enable()
        _SERVERS.append(self)

    # ----------------------------------------------------------- lifecycle
    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop(drain=exc_type is None)
        return False

    def start(self):
        """Start the batcher + worker threads (idempotent)."""
        if self._running:
            return self
        self._stopping = False
        self._running = True
        t = threading.Thread(target=self._batcher_loop,
                             name="mxtpu-serve-batcher", daemon=True)
        t.start()
        self._threads = [t]
        with self._batch_cond:
            self._worker_count = self.num_workers
        for i in range(self.num_workers):
            w = threading.Thread(target=self._worker_loop,
                                 name="mxtpu-serve-worker-%d" % i,
                                 daemon=True)
            w.start()
            self._threads.append(w)
        return self

    def stop(self, drain=True, timeout=60.0):
        """Stop the server.  ``drain=True`` (default) serves every
        already-accepted request first; ``drain=False`` fails pending
        requests with :class:`ServerStopped`.  New submissions are
        refused either way."""
        if not self._running:
            # a constructed-but-never-started (or already-stopped)
            # server must still leave the live registry — it would
            # otherwise pin the model forever and its zero-stats
            # section would shadow a real run's in module snapshot()
            if self in _SERVERS:
                _SERVERS.remove(self)
            return
        with self._cond:
            self._stopping = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    self._queued_samples -= req.n
                    req._fail(ServerStopped("server stopped before "
                                            "serving this request"))
            self._cond.notify_all()
        with self._batch_cond:
            self._batch_cond.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._running = False
        self._close_metrics()
        # drop out of the live registry; the final stats snapshot stays
        # readable (module snapshot() / diag dumps of a finished run)
        _FINAL[:] = [self.snapshot()]
        if self in _SERVERS:
            _SERVERS.remove(self)

    def warmup(self):
        """Build + compile every bucket executable up front (one padded
        all-zeros batch per bucket), so the first real request never
        pays a compile."""
        for b in self.buckets:
            fn = self._bucket_fn(b)
            inputs = {n: _device_put(np.zeros((b,) + s, self._model.dtypes[n]))
                      for n, s in self._model.sample_shapes.items()}
            _fetch(fn(inputs))
        return self

    # ------------------------------------------------------------- submit
    def submit(self, inputs):
        """Queue one request; returns a future with ``result(timeout)``.

        ``inputs``: one array (single-input models) or ``{name:
        array}``; every array carries a leading sample axis ``k`` (1 <=
        k <= the largest bucket) over the model's per-sample shape.
        Raises :class:`RequestRejected` up front on a full queue or a
        shape/name mismatch — shape churn is an explicit error, never a
        silent retrace of a new executable."""
        named = self._validate(inputs)
        n = next(iter(named.values())).shape[0]
        req = _Request(named, n)
        with self._cond:
            if self._stopping or not self._running:
                raise RequestRejected("server is not accepting requests"
                                      " (stopped)")
            if self._queued_samples + n > self.max_queue:
                self._count_reject("rejected_queue", n)
                raise RequestRejected(
                    "queue full (%d queued samples, max %d) — backpressure;"
                    " retry or add capacity" % (self._queued_samples,
                                               self.max_queue))
            depth = self._queued_samples
            self._queue.append(req)
            self._queued_samples += n
            # request x-ray: open the lifecycle record while still
            # holding _cond, so the batcher can never see a traced
            # request before its record exists.  Disabled: 1 dict read.
            if _reqtrace._state["on"]:
                _reqtrace.on_submit(req, depth)
            # one waiter on this condition in steady state (the
            # batcher) — notify() keeps the submit hot path cheap
            self._cond.notify()
        # flow-span tail of the submit seam, OUTSIDE _cond: the
        # profiler takes its own lock and must never nest under the
        # server condvar
        if _reqtrace._state["on"]:
            _reqtrace.on_submitted(req)
        return req

    def infer(self, inputs, timeout=60.0):
        """Blocking convenience: ``submit(inputs).result(timeout)``."""
        return self.submit(inputs).result(timeout)

    def _validate(self, inputs):
        shapes = self._model.sample_shapes
        if not isinstance(inputs, dict):
            if len(shapes) != 1:
                raise RequestRejected(
                    "model has inputs %s — pass a {name: array} dict"
                    % sorted(shapes))
            inputs = {next(iter(shapes)): inputs}
        unknown = set(inputs) - set(shapes)
        missing = set(shapes) - set(inputs)
        if unknown or missing:
            self._count_reject("rejected_shape")
            raise RequestRejected(
                "request inputs %s != model inputs %s"
                % (sorted(inputs), sorted(shapes)))
        named = {}
        n = None
        for name, arr in inputs.items():
            arr = np.asarray(arr, dtype=self._model.dtypes[name],
                             order="C")
            want = shapes[name]
            if arr.ndim != len(want) + 1 or tuple(arr.shape[1:]) != want:
                self._count_reject("rejected_shape")
                raise RequestRejected(
                    "input %r shape %s != (k,)+%s — requests carry an "
                    "explicit leading sample axis" % (name, arr.shape,
                                                      want))
            if n is None:
                n = arr.shape[0]
            elif arr.shape[0] != n:
                self._count_reject("rejected_shape")
                raise RequestRejected("inconsistent sample counts "
                                      "across inputs")
            named[name] = arr
        if not n or n > self.max_bucket:
            self._count_reject("rejected_shape")
            raise RequestRejected(
                "request sample count %s outside 1..%d (the largest "
                "bucket) — split large requests client-side"
                % (n, self.max_bucket))
        return named

    def _count_reject(self, kind, n=0):
        with self._stats_lock:
            self.stats[kind] += 1
        _rts.inc("serve_rejected")
        _rts.inc("serve_" + kind)
        # front-door rejects (queue/shape) never enter the pipeline —
        # record them as explicit lifecycle outcomes and SLO bad events
        # here; nonfinite rejections carry a full record and reach both
        # layers through _reject_nonfinite instead
        if kind != "rejected_nonfinite":
            if _reqtrace._state["on"]:
                _reqtrace.on_reject(kind, n)
            if _slo._state["on"]:
                _slo.on_request(None, False)

    # ------------------------------------------------------------ batching
    def _bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_bucket

    def _batcher_loop(self):
        """Form batches: greedily pack whole queued requests up to the
        largest bucket; dispatch immediately when the bucket is full or
        a worker sits idle, else wait up to ``max_wait`` for more
        arrivals (continuous batching: zero added latency unloaded,
        bucket-filling under load)."""
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait()
                if not self._queue:
                    break  # stopping and fully drained
                picked, total = self._pick_locked([], 0)
                deadline = time.perf_counter() + self.max_wait
                while total < self.max_bucket and not self._stopping:
                    if self._inflight < self.num_workers \
                            and not self._batchq:
                        break  # an idle worker: serve what we have now
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                    picked, total = self._pick_locked(picked, total)
                self._inflight += 1
            bucket = self._bucket_for(total)
            now = time.perf_counter()
            for r in picked:
                r.t_batched = now
            # batch-join seam: stamp bucket/batch-id, flow-step the
            # head-sampled members.  Disabled: one dict read per batch.
            if _reqtrace._state["on"]:
                _reqtrace.on_join(picked, bucket)
            with self._batch_cond:
                # bounded pipeline: at most one staged batch per worker
                # beyond what is executing, so accepted requests stay in
                # the accounted queue and ``max_queue`` is a real bound
                # on in-server backlog (explicit backpressure at submit)
                while len(self._batchq) >= self.num_workers:
                    self._batch_cond.wait(timeout=0.05)
                self._batchq.append((picked, total, bucket))
                self._batch_cond.notify()
        # wake the workers so they can observe the drained shutdown
        with self._batch_cond:
            self._batch_cond.notify_all()

    def _pick_locked(self, picked, total):
        while self._queue and total + self._queue[0].n <= self.max_bucket:
            r = self._queue.popleft()
            self._queued_samples -= r.n
            picked.append(r)
            total += r.n
        return picked, total

    def _bucket_fn(self, bucket):
        fn = self._bucket_fns.get(bucket)
        if fn is not None:
            return fn
        with self._bucket_lock:
            fn = self._bucket_fns.get(bucket)
            if fn is None:
                t0 = time.perf_counter()
                fn = self._bucket_fns[bucket] = self._model.build(bucket)
                with self._stats_lock:
                    self.stats["bucket_compiles"] += 1
                _rts.inc("serve_bucket_compiles")
                if _histogram._state["on"]:
                    _histogram.observe("serve:bucket_build",
                                       time.perf_counter() - t0)
        return fn

    # ------------------------------------------------------------- workers
    def _worker_loop(self):
        while True:
            with self._batch_cond:
                while not self._batchq:
                    if self._worker_count > self.num_workers:
                        # shrunk via set_workers: surplus workers
                        # retire when idle (never mid-batch)
                        self._worker_count -= 1
                        return
                    if self._stopping and self._batcher_done():
                        return
                    self._batch_cond.wait(timeout=0.1)
                picked, total, bucket = self._batchq.popleft()
                # a batcher blocked on the pipeline bound can stage the
                # next batch now
                self._batch_cond.notify_all()
            try:
                self._serve_batch(picked, total, bucket)
            except Exception as e:  # a bad batch must not kill the pool
                failed = 0
                for r in picked:
                    if not r.done():
                        r._fail(RequestRejected(
                            "batch execution failed: %s: %s"
                            % (type(e).__name__, e)))
                        failed += 1
                        if _reqtrace._state["on"]:
                            _reqtrace.on_done(r, "error", r.t_done)
                        if _slo._state["on"]:
                            _slo.on_request(
                                (r.t_done - r.t_submit) * 1e3, False)
                if failed:
                    with self._stats_lock:
                        self.stats["errors"] += failed
                warn_rate_limited(
                    _logger(), "serving:batch-error", WARN_INTERVAL,
                    "serving batch failed (%s: %s) — %d request(s) "
                    "rejected", type(e).__name__, e, len(picked))
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _batcher_done(self):
        return self._threads and not self._threads[0].is_alive()

    def _serve_batch(self, picked, total, bucket):
        t0 = time.perf_counter()
        hist_on = _histogram._state["on"]
        rt_on = _reqtrace._state["on"]
        slo_on = _slo._state["on"]
        if hist_on:
            for r in picked:
                _histogram.observe("serve:queue_wait",
                                   r.t_batched - r.t_submit)
        # host→device staging: one zero-padded host array per input
        # (rows past `total` are padding; their outputs are masked out
        # of the scatter below)
        inputs = {}
        bytes_in = 0
        for name, sshape in self._model.sample_shapes.items():
            dt = self._model.dtypes[name]
            buf = np.empty((bucket,) + sshape, dtype=dt)
            off = 0
            for r in picked:
                buf[off:off + r.n] = r.inputs[name]
                off += r.n
            if off < bucket:
                buf[off:] = 0  # the pad rows (masked out of the scatter)
            bytes_in += buf.nbytes
            inputs[name] = _device_put(buf)
        t_staged = time.perf_counter() if rt_on else None
        # device compute (async dispatch on real backends) …
        outs = self._bucket_fn(bucket)(inputs)
        # … then the one host-sync: the result scatter's batched fetch
        host_outs = _fetch(outs)
        t1 = time.perf_counter()
        if rt_on:
            # execution seam: worker/pad/staging/compute stamps, once
            # per batch (host floats only — the fetch already synced)
            _reqtrace.on_exec(picked, threading.current_thread().name,
                              bucket - total, t_staged, t1)
        bad_rows = self._sentinel(host_outs, total)
        bytes_out = sum(int(o.nbytes) for o in host_outs)
        off = 0
        completed = 0
        for r in picked:
            rows = slice(off, off + r.n)
            off += r.n
            if bad_rows is not None and bad_rows[rows].any():
                self._reject_nonfinite(r, bucket)
                continue
            r._complete([np.asarray(o[rows]) for o in host_outs])
            completed += 1
            if rt_on:
                _reqtrace.on_done(r, "ok", r.t_done)
            if slo_on:
                _slo.on_request((r.t_done - r.t_submit) * 1e3, True)
        if completed:
            with self._stats_lock:
                self.stats["completed"] += completed
        if hist_on:
            _histogram.observe("serve:batch", t1 - t0)
            _histogram.observe("serve:batch:b%d" % bucket, t1 - t0)
            for r in picked:
                _histogram.observe("serve:e2e", r.t_done - r.t_submit)
        self._account_batch(picked, total, bucket, t0, t1,
                            bytes_in, bytes_out)

    def _sentinel(self, host_outs, total):
        """Per-row non-finite mask over the valid rows of every float
        output (the serving analog of the health layer's device
        sentinels — here the batch is already on host for the scatter,
        so the check is a cheap vectorized reduction), or None when
        disabled/clean."""
        if not self._sentinel_on:
            return None
        bad = None
        for o in host_outs:
            if not np.issubdtype(o.dtype, np.floating):
                continue
            row_bad = ~np.isfinite(
                o[:total].reshape(total, -1)).all(axis=1)
            bad = row_bad if bad is None else (bad | row_bad)
        if bad is None or not bad.any():
            return None
        full = np.zeros(host_outs[0].shape[0], dtype=bool)
        full[:total] = bad
        return full

    def _reject_nonfinite(self, req, bucket):
        req._fail(RequestRejected(
            "served output contains non-finite values — response "
            "rejected (serving NaN sentinel; docs/SERVING.md)"))
        self._count_reject("rejected_nonfinite")
        rec = {"t": time.time(), "bucket": bucket, "n": req.n,
               "reason": "non-finite output"}
        self._rejections.append(rec)
        # flight-record the incident alongside training numerics
        # history when the health layer is live (ring read/append only
        # — never drains the monitor's device queue)
        mon = _health._GLOBAL[0] if _health._state["on"] and \
            _health._GLOBAL else None
        if mon is not None:
            mon.flight.append({"step": -1, "time": rec["t"],
                               "loss": None, "grad_norm": None,
                               "nan_total": 1.0, "inf_total": 0.0,
                               "first_bad": "serve:output",
                               "counters": None})
        warn_rate_limited(
            _logger(), "serving:nonfinite", WARN_INTERVAL,
            "non-finite values in a served output (bucket %d, %d "
            "sample(s)) — response rejected, not returned.  Check the "
            "model's numerics (docs/SERVING.md 'Output sentinels').",
            bucket, req.n)
        # sentinel hits are always-retained lifecycle outcomes and SLO
        # bad events (the request DID consume pipeline capacity)
        if _reqtrace._state["on"]:
            _reqtrace.on_done(req, "rejected_nonfinite", req.t_done)
        if _slo._state["on"]:
            _slo.on_request((req.t_done - req.t_submit) * 1e3, False)

    def _account_batch(self, picked, total, bucket, t0, t1,
                       bytes_in, bytes_out):
        wall = t1 - t0
        with self._stats_lock:
            s = self.stats
            s["requests"] += len(picked)
            s["samples"] += total
            s["batches"] += 1
            s["padded_rows"] += bucket - total
            pb = s["per_bucket"][bucket]
            pb["batches"] += 1
            pb["samples"] += total
            if s["first_batch_t"] is None:
                s["first_batch_t"] = t0
            s["last_batch_t"] = t1
            self._batch_seq += 1
            seq = self._batch_seq
        _rts.inc("serve_requests", len(picked))
        _rts.inc("serve_samples", total)
        _rts.inc("serve_batches")
        _rts.inc("serve_padded_rows", bucket - total)
        _rts.inc("serve_bytes_in", bytes_in)
        _rts.inc("serve_bytes_out", bytes_out)
        if self._metrics_path:
            waits = [r.t_batched - r.t_submit for r in picked]
            e2es = [r.t_done - r.t_submit for r in picked
                    if r.t_done is not None]
            self._write_metrics({
                "t": time.time(), "step": seq, "wall_ms": wall * 1e3,
                "throughput": (total / wall) if wall > 0 else None,
                "bucket": bucket, "n": total,
                "occupancy": total / bucket,
                "queue_wait_ms": sum(waits) / len(waits) * 1e3
                if waits else 0.0,
                "e2e_ms": sum(e2es) / len(e2es) * 1e3 if e2es else None,
                "queue_depth": self._queued_samples,
                "live_bytes": _dm.live_totals()[0]})
        # observability autopilot serving seam: gated reflexes over the
        # live serving stats, AFTER this batch's accounting committed.
        # Disabled: one dict read.
        if _autopilot._state["on"]:
            _autopilot.on_serve(self)

    # ------------------------------------------------------- JSONL export
    def _write_metrics(self, sample):
        """One atomic line per batch (the ``metrics_timeline`` JSONL
        convention: whole-record writes, rank-suffixed path, export
        goes dark with one warning on IO failure)."""
        with self._metrics_lock:
            f = self._metrics_file
            if f is None:
                path = rank_suffix_path(self._metrics_path)
                try:
                    f = open(path, "a", buffering=1)
                except OSError as e:
                    warn_rate_limited(
                        _logger(), "serving:metrics-open", 60,
                        "cannot open MXNET_TPU_SERVE_METRICS file %s "
                        "(%s) — serving timeline export disabled",
                        path, e)
                    self._metrics_path = None
                    return
                self._metrics_file = f
            try:
                f.write(json.dumps(sample, separators=(",", ":"),
                                   default=repr) + "\n")
            except (OSError, ValueError) as e:
                warn_rate_limited(
                    _logger(), "serving:metrics-write", 60,
                    "writing a serving timeline sample failed (%s) — "
                    "export disabled", e)
                self._metrics_path = None
                self._close_metrics_locked()

    def _close_metrics(self):
        with self._metrics_lock:
            self._close_metrics_locked()

    def _close_metrics_locked(self):
        f = self._metrics_file
        self._metrics_file = None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    # -------------------------------------------------------- runtime knobs
    def _note_adjust(self, knob, old, new):
        rec = {"t": time.time(), "knob": knob, "old": old, "new": new}
        with self._stats_lock:
            self.stats["knob_adjusts"] += 1
            self._adjustments.append(rec)
        _rts.inc("serve_knob_adjusts")

    def set_workers(self, n):
        """Adjust the pipeline worker count at runtime (thread-safe).
        Growing spawns workers immediately on a running server;
        shrinking lets surplus workers retire at their next idle wait
        (a worker never abandons a batch mid-execution).  The batcher
        reads ``num_workers`` fresh every iteration, so the dispatch
        and pipeline bounds follow without a restart."""
        n = max(1, int(n))
        # both conditions guard reads of ``num_workers`` (the batcher's
        # idle-worker check under _cond, the pipeline bound under
        # _batch_cond); no other path holds the two at once, so the
        # nested acquisition cannot deadlock
        with self._cond, self._batch_cond:
            old = self.num_workers
            self.num_workers = n
            spawn = 0
            if self._running and not self._stopping:
                spawn = max(0, n - self._worker_count)
                self._worker_count += spawn
            self._batch_cond.notify_all()
            self._cond.notify_all()
        for _ in range(spawn):
            w = threading.Thread(
                target=self._worker_loop,
                name="mxtpu-serve-worker-%d" % len(self._threads),
                daemon=True)
            w.start()
            self._threads.append(w)
        if n != old:
            self._note_adjust("workers", old, n)
        return n

    def set_max_wait_ms(self, ms):
        """Adjust the batch-formation wait at runtime (thread-safe:
        published under the batcher's condition, read fresh per
        batch)."""
        ms = max(0.0, float(ms))
        with self._cond:
            old = self.max_wait * 1e3
            self.max_wait = ms / 1e3
            self._cond.notify_all()
        if ms != old:
            self._note_adjust("max_wait_ms", round(old, 3),
                              round(ms, 3))
        return ms

    def set_max_queue(self, n):
        """Adjust the queued-sample bound (the load-shed threshold) at
        runtime (thread-safe: ``submit`` reads it fresh per request)."""
        n = max(1, int(n))
        old = self.max_queue
        self.max_queue = n
        if n != old:
            self._note_adjust("max_queue", old, n)
        return n

    # ----------------------------------------------------------- read side
    def queue_depth(self):
        """Currently queued samples (accepted, not yet batched)."""
        return self._queued_samples

    def snapshot(self):
        """JSON-ready serving stats: request/sample/batch totals,
        rejection counts by kind, per-bucket occupancy, bucket-
        executable compiles, derived QPS over the served window, and
        the recent rejection records.  Latency distributions live in
        the shared histogram section (``serve:*`` series)."""
        with self._stats_lock:
            s = dict(self.stats)
            per_bucket = {b: dict(v)
                          for b, v in self.stats["per_bucket"].items()}
        qps = None
        if s["first_batch_t"] is not None and s["samples"]:
            span = (s["last_batch_t"] or 0) - s["first_batch_t"]
            if span > 0:
                qps = s["samples"] / span
        out = {"enabled": True, "running": self._running,
               "name": self.name, "buckets": list(self.buckets),
               "workers": self.num_workers,
               "max_queue": self.max_queue,
               "max_wait_ms": self.max_wait * 1e3,
               "queue_depth": self._queued_samples,
               "requests": s["requests"], "samples": s["samples"],
               "batches": s["batches"],
               "padded_rows": s["padded_rows"],
               "bucket_compiles": s["bucket_compiles"],
               "rejected": {"queue": s["rejected_queue"],
                            "nonfinite": s["rejected_nonfinite"],
                            "shape": s["rejected_shape"]},
               # per-outcome breakdown: every request a client ever
               # handed us lands in exactly one of these buckets
               "outcomes": {"ok": s["completed"],
                            "rejected_queue": s["rejected_queue"],
                            "rejected_shape": s["rejected_shape"],
                            "rejected_nonfinite":
                                s["rejected_nonfinite"],
                            "error": s["errors"]},
               "per_bucket": {str(b): v for b, v in per_bucket.items()
                              if v["batches"]},
               "qps": qps,
               "knob_adjusts": s["knob_adjusts"],
               "adjustments": list(self._adjustments)[-8:],
               "rejections": list(self._rejections)[-16:]}
        mean_occ = None
        if s["batches"]:
            # occupancy = valid rows / bucket rows over the whole run
            total_rows = sum(b * v["batches"]
                             for b, v in per_bucket.items())
            if total_rows:
                mean_occ = s["samples"] / total_rows
        out["mean_occupancy"] = mean_occ
        return out


# ------------------------------------------------------- module surface


def servers():
    """Every LIVE (not yet stopped) server, oldest first."""
    return list(_SERVERS)


def snapshot():
    """The newest live server's :meth:`InferenceServer.snapshot`, the
    most recently stopped server's final stats when none is live, or a
    disabled stub — what ``runtime_stats.snapshot()['serving']``
    embeds (via ``sys.modules``, so a process that never imported the
    serving layer pays nothing)."""
    if _SERVERS:
        return _SERVERS[-1].snapshot()
    if _FINAL:
        return dict(_FINAL[0])
    return {"enabled": False}


def reset():
    """Forget every live server and retained final snapshot (tests)."""
    _SERVERS.clear()
    _FINAL.clear()
    from .log import reset_rate_limits

    reset_rate_limits("serving:")
