"""Small shared utilities (reference: python/mxnet/util.py)."""

from __future__ import annotations

import os

__all__ = ["makedirs"]


def makedirs(d):
    """Recursively create directories, tolerating existing ones
    (reference: util.py makedirs)."""
    os.makedirs(d, exist_ok=True)
