"""Embedded-interpreter half of the C predict ABI (MXTPUPred*).

`native/src/predict.cc` drives the jax runtime from plain C by embedding
CPython (the TPU deployment analog of the reference's self-contained
`c_predict_api.h` build: on TPU the inference runtime IS jax/XLA/PJRT,
so the C ABI hosts an interpreter instead of a second engine).  All
arguments cross the boundary as integer addresses; this module reads and
writes those buffers with ctypes.  Every entry point is no-raise: errors
are reported through the (status, errbuf) out-parameters.

Reference: include/mxnet/c_predict_api.h, src/c_api/c_predict_api.cc.
"""

from __future__ import annotations

import ctypes
import traceback

_predictors = {}
_next_id = [1]

_MAX_NDIM = 16


def _status(status_addr, err_addr, err_cap, code, msg=""):
    if err_addr and msg:
        raw = msg.encode("utf-8", "replace")[: max(0, err_cap - 1)] + b"\0"
        ctypes.memmove(err_addr, raw, len(raw))
    ctypes.cast(status_addr, ctypes.POINTER(ctypes.c_int64))[0] = code


def _read_shapes(nkeys, keys_addr, indptr_addr, shapes_addr):
    keys = ctypes.cast(keys_addr, ctypes.POINTER(ctypes.c_char_p))
    indptr = ctypes.cast(indptr_addr, ctypes.POINTER(ctypes.c_uint32))
    sdata = ctypes.cast(shapes_addr, ctypes.POINTER(ctypes.c_uint32))
    shapes = {}
    for i in range(nkeys):
        name = keys[i].decode("utf-8")
        shapes[name] = tuple(int(sdata[j])
                             for j in range(indptr[i], indptr[i + 1]))
    return shapes


def c_create(json_addr, json_len, param_addr, param_len, dev_type, dev_id,
             nkeys, keys_addr, indptr_addr, shapes_addr,
             out_id_addr, status_addr, err_addr, err_cap):
    try:
        from .predictor import Predictor

        json_str = ctypes.string_at(json_addr, json_len).decode("utf-8")
        param_bytes = ctypes.string_at(param_addr, param_len)
        shapes = _read_shapes(nkeys, keys_addr, indptr_addr, shapes_addr)
        dev = {1: "cpu", 2: "tpu", 3: "cpu"}.get(dev_type, "cpu")
        pred = Predictor(json_str, param_bytes, shapes, dev, dev_id)
        pid = _next_id[0]
        _next_id[0] += 1
        _predictors[pid] = {"pred": pred, "inputs": {}}
        ctypes.cast(out_id_addr, ctypes.POINTER(ctypes.c_uint64))[0] = pid
        _status(status_addr, err_addr, err_cap, 0)
    except Exception:
        _status(status_addr, err_addr, err_cap, -1, traceback.format_exc())


def c_set_input(pid, key_addr, data_addr, size,
                status_addr, err_addr, err_cap):
    try:
        import numpy as np

        st = _predictors[pid]
        key = ctypes.string_at(key_addr).decode("utf-8")
        pred = st["pred"]
        if key not in pred.get_input_names():
            raise ValueError("unknown input '%s' (expected %s)"
                             % (key, pred.get_input_names()))
        shape = tuple(pred._exec.arg_dict[key].shape)
        n = int(np.prod(shape)) if shape else 1
        if int(size) != n:
            raise ValueError("input '%s': got %d elements, bound shape %s "
                             "needs %d" % (key, size, shape, n))
        flat = np.ctypeslib.as_array(
            ctypes.cast(data_addr, ctypes.POINTER(ctypes.c_float)), (n,))
        st["inputs"][key] = flat.reshape(shape).copy()
        _status(status_addr, err_addr, err_cap, 0)
    except Exception:
        _status(status_addr, err_addr, err_cap, -1, traceback.format_exc())


def c_forward(pid, status_addr, err_addr, err_cap):
    try:
        st = _predictors[pid]
        st["pred"].forward(**st["inputs"])
        _status(status_addr, err_addr, err_cap, 0)
    except Exception:
        _status(status_addr, err_addr, err_cap, -1, traceback.format_exc())


def c_get_output_shape(pid, index, out_dims_addr,
                       status_addr, err_addr, err_cap):
    """Writes [ndim, dim0, dim1, ...] into a uint32[1+_MAX_NDIM] buffer."""
    try:
        pred = _predictors[pid]["pred"]
        shape = pred.get_output_shape(index)
        if len(shape) > _MAX_NDIM:
            raise ValueError("output ndim %d exceeds %d"
                             % (len(shape), _MAX_NDIM))
        buf = ctypes.cast(out_dims_addr, ctypes.POINTER(ctypes.c_uint32))
        buf[0] = len(shape)
        for i, d in enumerate(shape):
            buf[1 + i] = d
        _status(status_addr, err_addr, err_cap, 0)
    except Exception:
        _status(status_addr, err_addr, err_cap, -1, traceback.format_exc())


def c_get_output(pid, index, data_addr, size,
                 status_addr, err_addr, err_cap):
    try:
        import numpy as np

        pred = _predictors[pid]["pred"]
        out = np.ascontiguousarray(pred.get_output(index),
                                   dtype=np.float32)
        if int(size) != out.size:
            raise ValueError("output %d has %d elements, caller buffer %d"
                             % (index, out.size, size))
        ctypes.memmove(data_addr, out.ctypes.data, out.nbytes)
        _status(status_addr, err_addr, err_cap, 0)
    except Exception:
        _status(status_addr, err_addr, err_cap, -1, traceback.format_exc())


def c_reshape(pid, nkeys, keys_addr, indptr_addr, shapes_addr,
              out_id_addr, status_addr, err_addr, err_cap):
    try:
        st = _predictors[pid]
        shapes = _read_shapes(nkeys, keys_addr, indptr_addr, shapes_addr)
        new = st["pred"]._reshape_clone(shapes)
        nid = _next_id[0]
        _next_id[0] += 1
        _predictors[nid] = {"pred": new, "inputs": {}}
        ctypes.cast(out_id_addr, ctypes.POINTER(ctypes.c_uint64))[0] = nid
        _status(status_addr, err_addr, err_cap, 0)
    except Exception:
        _status(status_addr, err_addr, err_cap, -1, traceback.format_exc())


def c_free(pid, status_addr, err_addr, err_cap):
    try:
        _predictors.pop(pid, None)
        _status(status_addr, err_addr, err_cap, 0)
    except Exception:
        _status(status_addr, err_addr, err_cap, -1, traceback.format_exc())
