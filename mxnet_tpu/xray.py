"""Fused-step X-ray — named-scope cost attribution inside the donated
whole-step program.

PR 11/14 moved the whole training iteration (forward + backward +
optimizer update + ZeRO collectives) into ONE donated XLA program, so
the per-op telemetry reads "compiled_step: ~100%" with nothing inside.
This module restores per-region attribution:

* **Trace time** — the canonical regions of the fused step are wrapped
  in ``jax.named_scope``: every Gluon block's forward (at the
  ``Block.__call__`` staging seam, scope = block name path), the loss
  (:data:`REGION_LOSS`), the ``value_and_grad`` backward
  (:data:`GRAD_MARKER`), the fused optimizer update
  (:data:`REGION_OPT`) and the ZeRO all-gather / grad-norm /
  reduce-scatter regions in ``parallel/gluon_step.py``.  XLA carries
  the scope path into every HLO instruction's
  ``metadata={op_name="jit(f)/.../<scope>/<primitive>"}``, through
  fusion: fused computations list their inner instructions with their
  own metadata.

* **Compile time** — ``compiled_step.py``'s two AOT
  ``lower(...).compile()`` sites call :func:`analyze`, which parses the
  optimized HLO text and attributes per-instruction flops, bytes
  accessed, output bytes and collective bytes back to canonical scopes
  (``forward/<block path>``, ``backward/<block path>``, ``optimizer``,
  ``zero_allgather`` …).  JAX's AD markers are folded in:
  ``jvp(<scope>)`` instructions stay forward, anything under
  ``transpose(...)`` is the backward of that scope.

The table obeys the same conservation contract ``stepstats`` pins for
wall time: an explicit ``unattributed`` remainder absorbs whatever the
per-instruction estimates did not cover, and when the estimates
OVERSHOOT the whole-program ``cost_analysis`` totals they are scaled
down (and the metric listed under ``overattributed``) — scope sums can
never exceed program totals, sum(scopes) + unattributed == totals.

Attribution runs only at the two compile sites, gated on the same
``cost_capture_active()`` switch as cost analysis — never on the step
hot path.  Trace-time annotation is on by default and costs one dict
read per block call when disabled; ``MXNET_TPU_XRAY=0`` is the kill
switch (``=1`` force-enables after a programmatic :func:`disable`).
Docs: docs/OBSERVABILITY.md "Fused-step X-ray".
"""

from __future__ import annotations

import contextlib
import itertools
import os
import re
import threading

__all__ = ["enable", "disable", "is_enabled", "scope", "block_scope",
           "canonical_scope", "attribute", "analyze",
           "REGION_LOSS", "REGION_OPT", "GRAD_MARKER",
           "REGION_ZERO_AG", "REGION_ZERO_RS", "REGION_ZERO_GNORM"]

# single-dict-read disabled check (the stepstats/profiler convention):
# Block.__call__ and the scope() helpers consult _state["on"] and do
# nothing else when off — pinned by the bench gate
_state = {"on": True}

_SEQ = itertools.count(1)

# canonical region names the fused-step tracers wrap
REGION_LOSS = "loss"
REGION_OPT = "optimizer"
REGION_ZERO_AG = "zero_allgather"
REGION_ZERO_RS = "zero_reduce_scatter"
REGION_ZERO_GNORM = "zero_gradnorm"
# wrapper scope around the value_and_grad CALL — a direction marker
# only, filtered out of canonical paths (transpose() in the op_name is
# what actually flags backward)
GRAD_MARKER = "grad"

# regions reported as-is, without a forward/backward direction prefix:
# the optimizer update and the ZeRO data movement are step phases of
# their own, whichever side of the transpose they land on
_PLAIN_REGIONS = frozenset(
    {REGION_OPT, REGION_ZERO_AG, REGION_ZERO_RS, REGION_ZERO_GNORM})

_NULL = contextlib.nullcontext()


def enable():
    """(Re-)arm trace-time scope annotation (the default state)."""
    _state["on"] = True


def disable():
    """Stop annotating traces; already-captured tables remain."""
    _state["on"] = False


def is_enabled():
    return _state["on"]


def _activate_from_env():
    """``MXNET_TPU_XRAY=0`` kills annotation, ``=1`` force-enables —
    called from runtime_stats' import-time activation chain."""
    v = os.environ.get("MXNET_TPU_XRAY")
    if v == "0":
        disable()
    elif v == "1":
        enable()


# ------------------------------------------------------------ trace side
def scope(name):
    """``jax.named_scope(name)`` when armed, a no-op context otherwise.

    Trace-time only — the returned context manager never appears on the
    executed step path."""
    if not _state["on"]:
        return _NULL
    import jax

    return jax.named_scope(name)


# Block names are FULL prefixes ("sequential0_dense0" for a child of
# "sequential0"), so a naive nesting would render
# "sequential0/sequential0_dense0".  A per-thread stack of the raw
# names lets each child strip its parent's prefix and the scope path
# read "sequential0/dense0".
_tls = threading.local()


@contextlib.contextmanager
def block_scope(block):
    """Named scope for one Gluon block's forward, labelled with the
    block name minus the enclosing block's prefix (so nested paths
    compose as ``parent/child``)."""
    import jax

    name = getattr(block, "_name", None) or type(block).__name__.lower()
    stack = getattr(_tls, "blocks", None)
    if stack is None:
        stack = _tls.blocks = []
    label = name
    if stack:
        parent = stack[-1]
        if label.startswith(parent + "_"):
            label = label[len(parent) + 1:]
    label = label or name
    stack.append(name)
    try:
        with jax.named_scope(label):
            yield
    finally:
        stack.pop()


# ---------------------------------------------------------- compile side
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(
    r"\b(f8e4m3fn|f8e5m2|pred|bf16|f16|f32|f64|c64|c128|"
    r"s4|s8|s16|s32|s64|u4|u8|u16|u32|u64)\[([0-9,]*)\]")

# one HLO instruction: "  [ROOT] %name = <shape> opcode(operands...)"
# — shape is either one array ("f32[8,16]{1,0}") or a tuple
# ("(f32[8]{0}, f32[])"); array layouts use braces, so the tuple never
# nests parens
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+ = (?P<shape>\([^)]*\)|\S+) "
    r"(?P<op>[a-z][\w\-]*)\(")

_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CDIMS_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_DIMLABELS_RE = re.compile(r"dim_labels=([\w>\-]+)")
_WRAPPER_RE = re.compile(
    r"\b(transpose|jvp|vjp|vmap|pmap|remat|checkpoint|pjit)\(")

# bookkeeping opcodes that move no data of their own (or whose cost is
# carried by their inner/paired instruction): the fusion container is
# skipped because its fused computation's instructions are parsed with
# their own metadata
_SKIP_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "fusion", "call", "after-all", "partition-id",
    "replica-id", "domain", "get-dimension-size", "opt-barrier",
    "add-dependency",
})

_COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
})


def _shapes(s):
    """Every ``dtype[dims]`` token in ``s`` as (dims tuple, elems,
    bytes) triples."""
    out = []
    for m in _SHAPE_RE.finditer(s):
        dims = tuple(int(d) for d in m.group(2).split(",") if d)
        elems = 1
        for d in dims:
            elems *= d
        out.append((dims, elems, elems * _DTYPE_BYTES.get(m.group(1), 4)))
    return out


def _unwrap(op_name):
    """Flatten JAX transform markers out of an op_name: ``jvp(X)`` →
    ``X`` (still forward), ``transpose(X)`` → ``X`` with the backward
    flag raised."""
    s = op_name
    backward = False
    while True:
        m = _WRAPPER_RE.search(s)
        if m is None:
            return s, backward
        if m.group(1) == "transpose":
            backward = True
        depth, j = 1, m.end()
        while j < len(s) and depth:
            if s[j] == "(":
                depth += 1
            elif s[j] == ")":
                depth -= 1
            j += 1
        s = s[:m.start()] + s[m.end():j - 1] + s[j:]


def canonical_scope(op_name):
    """The canonical scope for one instruction's ``op_name`` metadata,
    or ``None`` when it carries no user scope (→ unattributed).

    ``jit(...)`` components and the :data:`GRAD_MARKER` wrapper are
    dropped, transform markers are unwrapped (``transpose`` anywhere
    flags backward), the trailing component (the primitive name) is
    stripped, and the remaining path gets a ``forward/`` or
    ``backward/`` prefix — except the plain step regions (optimizer,
    zero_*), which report as-is."""
    if not op_name:
        return None
    s, backward = _unwrap(op_name)
    parts = [p for p in s.split("/")
             if p and not p.startswith("jit(") and p != GRAD_MARKER]
    if len(parts) < 2:
        return None  # just the primitive name: no user scope
    path = parts[:-1]
    if path[0] in _PLAIN_REGIONS:
        return "/".join(path)
    return ("backward/" if backward else "forward/") + "/".join(path)


def _instr_cost(op, shape_str, operand_str, attr_str):
    """(flops, bytes_accessed, output_bytes, collective_bytes)
    estimates for one instruction from its shapes + attributes."""
    outs = _shapes(shape_str)
    out_elems = sum(e for _, e, _ in outs)
    out_bytes = sum(b for _, _, b in outs)
    opnds = _shapes(operand_str)
    opnd_bytes = sum(b for _, _, b in opnds)
    base = op[:-6] if op.endswith("-start") else op
    flops = 0.0
    coll = 0.0
    if base == "dot":
        flops = 2.0 * out_elems
        m = _CDIMS_RE.search(attr_str)
        if m and opnds:
            lhs_dims = opnds[0][0]
            for d in m.group(1).split(","):
                if d and int(d) < len(lhs_dims):
                    flops *= lhs_dims[int(d)]
    elif base == "convolution":
        flops = 2.0 * out_elems
        m = _DIMLABELS_RE.search(attr_str)
        if m and len(opnds) > 1 and "_" in m.group(1):
            rhs_lab = m.group(1).split("_", 1)[1].split("->", 1)[0]
            rhs_dims = opnds[1][0]
            if len(rhs_lab) == len(rhs_dims):
                for ch, d in zip(rhs_lab, rhs_dims):
                    if ch != "o":  # Cin and the kernel spatial dims
                        flops *= d
    elif base in ("reduce", "reduce-window"):
        flops = float(sum(e for _, e, _ in opnds))
    elif base in _COLLECTIVE_OPS:
        coll = float(max(opnd_bytes, out_bytes))
    elif base in ("custom-call", "rng-bit-generator", "copy", "iota",
                  "broadcast", "reshape", "transpose", "slice",
                  "concatenate", "pad", "gather", "scatter",
                  "dynamic-slice", "dynamic-update-slice"):
        flops = 0.0  # data movement / opaque: bytes carry the cost
    else:
        flops = float(out_elems)  # elementwise default
    return flops, float(opnd_bytes + out_bytes), float(out_bytes), coll


def _zero_rec():
    return {"flops": 0.0, "bytes": 0.0, "output_bytes": 0.0,
            "collective_bytes": 0.0, "instructions": 0}


def attribute(hlo_text):
    """Parse optimized HLO text into raw per-scope cost estimates.

    Returns ``(scopes, unattributed, parsed)`` — ``scopes`` maps
    canonical scope → {flops, bytes, output_bytes, collective_bytes,
    instructions}, instructions without a user scope land in
    ``unattributed``, ``parsed`` counts costed instructions."""
    scopes = {}
    un = _zero_rec()
    parsed = 0
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        op = m.group("op")
        if op in _SKIP_OPS or op.endswith("-done") \
                or op.endswith("-update"):
            continue
        rest = line[m.end():]
        depth, j = 1, 0
        while j < len(rest) and depth:
            if rest[j] == "(":
                depth += 1
            elif rest[j] == ")":
                depth -= 1
            j += 1
        operand_str, attr_str = rest[:j], rest[j:]
        flops, bacc, obytes, coll = _instr_cost(
            op, m.group("shape"), operand_str, attr_str)
        om = _OPNAME_RE.search(attr_str)
        sc = canonical_scope(om.group(1)) if om else None
        rec = scopes.setdefault(sc, _zero_rec()) if sc else un
        rec["flops"] += flops
        rec["bytes"] += bacc
        rec["output_bytes"] += obytes
        rec["collective_bytes"] += coll
        rec["instructions"] += 1
        parsed += 1
    return scopes, un, parsed


def analyze(compiled, cost=None, label="compiled_step", zero=False):
    """Build the conservation-normalized per-scope table for one
    compiled whole-step program.

    ``cost`` is the program's ``compiled_cost`` record; when it carries
    whole-program ``flops`` / ``bytes_accessed`` truth, per-scope
    estimates that fall short leave the difference in ``unattributed``
    and estimates that overshoot are scaled down (metric listed in
    ``overattributed``) — either way sum(scopes) + unattributed ==
    totals.  Metrics with no truth fall back to the estimate sums and
    are listed in ``estimated``."""
    if not _state["on"]:
        return None
    scopes, un, parsed = attribute(compiled.as_text())
    totals = {}
    estimated = []
    over = []
    for metric, ckey in (("flops", "flops"), ("bytes", "bytes_accessed")):
        attr = sum(rec[metric] for rec in scopes.values())
        true = None
        if cost is not None and ckey in cost:
            try:
                true = float(cost[ckey])
            except (TypeError, ValueError):
                true = None
        if true is None:
            totals[ckey] = attr + un[metric]
            estimated.append(ckey)
        elif attr <= true:
            totals[ckey] = true
            un[metric] = true - attr
        else:
            # estimates overshoot the measured program total (fusion
            # intermediates overcount real traffic): scale every bucket
            # down proportionally, unattributed included, so the sum
            # still lands exactly on the truth
            scale = true / (attr + un[metric])
            for rec in scopes.values():
                rec[metric] *= scale
            un[metric] *= scale
            totals[ckey] = true
            over.append(ckey)
    tf, tb = totals["flops"], totals["bytes_accessed"]
    for rec in list(scopes.values()) + [un]:
        rec["flops_share"] = rec["flops"] / tf if tf else 0.0
        rec["bytes_share"] = rec["bytes"] / tb if tb else 0.0
    return {
        "seq": next(_SEQ),
        "label": label,
        "zero": bool(zero),
        "instructions": parsed,
        "totals": totals,
        "estimated": estimated,
        "overattributed": over,
        "scopes": scopes,
        "unattributed": un,
    }
