"""Lock-free log2-bucketed latency histograms (distributed telemetry).

``runtime_stats.py`` counts *how often* things happen; this module
records *how long* they take, as full distributions rather than sums —
the primitive the distributed roadmap items (straggler detection,
serving-latency SLOs, cost-model validation per arXiv:2301.13062) need.
Counters alone cannot show that rank 3's push RTT has a fat tail.

Design: one histogram is a dict of power-of-two buckets (``frexp``
exponent → count: bucket ``e`` covers ``[2^(e-1), 2^e)`` seconds) plus
an exact count / sum / min / max.  All mutation is plain GIL-atomic
dict and attribute increments — no locks anywhere, same hot-path
contract as ``runtime_stats`` (exact on one thread, best-effort under
concurrency).  Percentiles are derived by rank-interpolating inside
the bucket that holds the target rank, with the bucket bounds tightened
by the exact observed min/max — so a histogram whose samples share one
value reports that value exactly, and any derived percentile is within
one bucket (a factor of 2) of the true order statistic.  Histograms
merge associatively (bucket-count addition), which is what lets
``tools/diagnose.py --cluster`` fold per-rank dumps into one
cluster-wide distribution.

Feeding points (guard-first — one dict read when disabled, bench-gated
in ``tests/test_bench_gate.py``): dist-kvstore push/pull RTT per shard
(``kvstore/ps.py``), cache-warm dispatch wall-time
(``runtime_stats.add_dispatch_seconds``), ``DataIter.__next__`` wait
(``io/io.py``), checkpoint write time (``checkpoint.py``), and
``gluon.Trainer.step`` wall-time.  The parameter server additionally
keeps always-on private ``Histogram`` instances for its apply/handle
latency (network RTT dominates there; see ``PSServer.stats_snapshot``).

Environment variables
---------------------
``MXNET_TPU_HISTOGRAMS``  ``1`` enables collection from import, ``0``
    forces it off; unset, collection auto-enables when
    ``MXNET_TPU_PROFILE`` or ``MXNET_TPU_DIAG`` is set (those runs are
    already paying for timestamps).
``MXNET_TPU_STRAGGLER_RATIO``  a shard is called a straggler when its
    RTT p99 exceeds this multiple of the median shard p99 (default 3).
``MXNET_TPU_STRAGGLER_MIN_SAMPLES``  per-shard observations required
    before the live straggler check fires (default 32).
``MXNET_TPU_STRAGGLER_INTERVAL``  minimum seconds between live
    straggler warnings (default 60).
"""

from __future__ import annotations

import math
import os

__all__ = ["Histogram", "enable", "disable", "is_enabled", "observe",
           "get", "snapshot", "reset", "merge_snapshots",
           "detect_straggler", "bucket_index", "bucket_bounds"]

# straggler-detection knobs (module attrs so tests can monkeypatch)
STRAGGLER_RATIO = float(os.environ.get("MXNET_TPU_STRAGGLER_RATIO", "3"))
STRAGGLER_MIN_SAMPLES = int(os.environ.get(
    "MXNET_TPU_STRAGGLER_MIN_SAMPLES", "32"))
STRAGGLER_WARN_INTERVAL = float(os.environ.get(
    "MXNET_TPU_STRAGGLER_INTERVAL", "60"))

# bucket for values <= 0 (a degenerate but legal observation): below
# every subnormal exponent, so it always sorts first
_ZERO_BUCKET = -1100

# mxlint: disable=thread-shared-state -- single-key GIL-atomic enable flag; the guard-first contract forbids a lock on the disabled path
_state = {"on": False}
# name -> Histogram; mutated with GIL-atomic ops only
# mxlint: disable=thread-shared-state -- best-effort telemetry histograms: a lost increment under concurrent observe() is accepted noise (runtime_stats contract)
_HISTS: dict = {}


def bucket_index(value):
    """Bucket exponent for ``value``: the ``e`` with ``value`` in
    ``[2^(e-1), 2^e)`` (``frexp``'s exponent), or the zero bucket for
    values <= 0."""
    if value <= 0.0:
        return _ZERO_BUCKET
    return math.frexp(value)[1]


def bucket_bounds(index):
    """``(lo, hi)`` seconds covered by bucket ``index``."""
    if index == _ZERO_BUCKET:
        return (0.0, 0.0)
    return (math.ldexp(0.5, index), math.ldexp(1.0, index))


class Histogram:
    """One log2-bucketed distribution with exact count/sum/min/max.

    Mutation is lock-free (GIL-atomic increments); reads
    (:meth:`snapshot`, :meth:`percentile`) copy the bucket dict first,
    so a concurrent observe can never torn-read a derived stat."""

    __slots__ = ("buckets", "count", "total", "min", "max")

    def __init__(self):
        self.buckets = {}
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0

    def observe(self, value):
        """Record one sample (seconds)."""
        b = bucket_index(value)
        buckets = self.buckets
        buckets[b] = buckets.get(b, 0) + 1
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def merge(self, other):
        """Fold ``other`` (a Histogram) into this one — associative and
        commutative up to float-sum rounding, the property the
        cross-rank merge relies on."""
        for b, c in list(other.buckets.items()):
            self.buckets[b] = self.buckets.get(b, 0) + c
        self.count += other.count
        self.total += other.total
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        return self

    def percentile(self, q):
        """Derived q-th percentile: rank interpolation inside the
        bucket holding rank ``q/100 * count``, with bucket bounds
        tightened by the exact min/max (all-equal samples → exact)."""
        count = self.count
        if not count:
            return None
        buckets = dict(self.buckets)
        target = count * q / 100.0
        cum = 0.0
        for b in sorted(buckets):
            c = buckets[b]
            nxt = cum + c
            if nxt >= target:
                lo, hi = bucket_bounds(b)
                lo = max(lo, self.min)
                hi = min(hi, self.max)
                if hi < lo:
                    hi = lo
                frac = (target - cum) / c if c else 1.0
                return lo + (hi - lo) * frac
            cum = nxt
        return self.max

    def snapshot(self):
        """JSON-ready dict: exact count/sum/min/max, derived mean and
        p50/p90/p99, and the raw buckets (for merging)."""
        count = self.count
        out = {"count": count, "sum": self.total,
               "min": self.min if count else None,
               "max": self.max if count else None,
               "mean": (self.total / count) if count else None,
               "buckets": {str(b): c for b, c in list(self.buckets.items())}}
        for q, key in ((50, "p50"), (90, "p90"), (99, "p99")):
            out[key] = self.percentile(q)
        return out

    @classmethod
    def from_snapshot(cls, snap):
        """Rebuild a mergeable Histogram from :meth:`snapshot` output
        (bucket keys may be strings after a JSON round-trip)."""
        h = cls()
        h.buckets = {int(b): int(c)
                     for b, c in (snap.get("buckets") or {}).items()}
        h.count = int(snap.get("count", 0))
        h.total = float(snap.get("sum", 0.0))
        if h.count:
            h.min = float(snap["min"]) if snap.get("min") is not None \
                else math.inf
            h.max = float(snap["max"]) if snap.get("max") is not None \
                else 0.0
        return h


def merge_snapshots(snaps):
    """Merge a list of :meth:`Histogram.snapshot` dicts (possibly
    JSON-round-tripped) into one snapshot dict — the per-rank →
    cluster fold."""
    merged = Histogram()
    for s in snaps:
        merged.merge(Histogram.from_snapshot(s))
    return merged.snapshot()


# ------------------------------------------------------------ registry


def enable():
    """Turn collection on; also turns on the dispatch layer's cache-warm
    timing (``runtime_stats.DIAG_TIMING``) so the warm-dispatch
    histogram has a feed even without the profiler/DIAG running."""
    _state["on"] = True
    from . import runtime_stats as _rts

    _rts.DIAG_TIMING = True


def disable():
    """Turn collection off (existing histograms are kept; ``reset()``
    drops them).  Dispatch timing reverts to its env-derived state —
    unless step-time attribution (``stepstats``) still needs it."""
    _state["on"] = False
    from . import runtime_stats as _rts
    from . import stepstats as _stepstats

    _rts.DIAG_TIMING = bool(os.environ.get("MXNET_TPU_DIAG")) \
        or _stepstats._state["on"]


def is_enabled():
    return _state["on"]


def get(name):
    """The named histogram (created on first use)."""
    h = _HISTS.get(name)
    if h is None:
        h = _HISTS[name] = Histogram()
    return h


def observe(name, value):
    """Record one sample into the named histogram — ONE dict read and
    nothing else while collection is off (the bench-gated contract;
    callers on hot paths guard on ``_state["on"]`` themselves before
    taking timestamps)."""
    if not _state["on"]:
        return
    h = _HISTS.get(name)
    if h is None:
        h = _HISTS[name] = Histogram()
    h.observe(value)


def snapshot():
    """``{name: histogram-snapshot-dict}`` for every live histogram."""
    return {name: h.snapshot() for name, h in list(_HISTS.items())}


def reset():
    """Drop every histogram (tests)."""
    _HISTS.clear()


# --------------------------------------------------- straggler detection


def median_of_others(p99s, worst_name):
    """Median p99 of every group member EXCEPT the worst.  Comparing
    the worst against the median *including itself* caps the
    detectable ratio at 2x for two-member groups (the worst drags its
    own baseline up); excluding it keeps one straggler detectable at
    any group size."""
    import statistics

    others = [p for n, p in p99s if n != worst_name]
    return statistics.median(others) if others else None


def detect_straggler(prefix, min_samples=None, ratio=None):
    """Among live histograms whose name starts with ``prefix`` (one per
    shard/rank), return ``{"name", "p99", "median_p99", "ratio"}`` for
    the slowest when its p99 exceeds ``ratio`` × the median p99 of the
    OTHER members — else None.  Needs >= 2 group members with at least
    ``min_samples`` observations each."""
    min_samples = STRAGGLER_MIN_SAMPLES if min_samples is None \
        else min_samples
    ratio = STRAGGLER_RATIO if ratio is None else ratio
    group = [(name, h) for name, h in list(_HISTS.items())
             if name.startswith(prefix) and h.count >= min_samples]
    if len(group) < 2:
        return None
    p99s = [(name, h.percentile(99)) for name, h in group]
    p99s = [(n, p) for n, p in p99s if p is not None]
    if len(p99s) < 2:
        return None
    worst_name, worst = max(p99s, key=lambda np_: np_[1])
    med = median_of_others(p99s, worst_name)
    if not med or med <= 0 or worst <= ratio * med:
        return None
    return {"name": worst_name, "p99": worst, "median_p99": med,
            "ratio": worst / med}


def _activate_from_env():
    """Import-time arming — called by ``runtime_stats`` once its module
    globals exist (enable() writes ``runtime_stats.DIAG_TIMING``)."""
    flag = os.environ.get("MXNET_TPU_HISTOGRAMS")
    if flag == "0":
        return False
    if flag == "1" or os.environ.get("MXNET_TPU_PROFILE") \
            or os.environ.get("MXNET_TPU_DIAG"):
        enable()
        return True
    return False
