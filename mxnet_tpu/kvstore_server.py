"""Parameter-server process bootstrap.

Reference: python/mxnet/kvstore_server.py:28 — when DMLC_ROLE=server the
interpreter becomes a blocking PS server instead of running user code.
Launched by `tools/launch.py -s N` (or run directly:
`DMLC_ROLE=server python -m mxnet_tpu.kvstore_server`).
"""

from __future__ import annotations

import os

__all__ = ["init_server", "main"]


def init_server(controller=None):
    """If this process's DMLC_ROLE is 'server', serve until stopped and
    return True; otherwise return False (worker processes continue).

    controller(head, body), when given, handles app-level server
    commands (reference: KVStore::RunServer's controller argument)."""
    if os.environ.get("DMLC_ROLE") != "server":
        return False
    from .kvstore.ps import run_server, set_app_controller

    if controller is not None:
        set_app_controller(controller)
    run_server()
    return True


def main():
    os.environ.setdefault("DMLC_ROLE", "server")
    init_server()


if __name__ == "__main__":
    main()
