"""Device-buffer tracker — live HBM accounting for NDArray buffers.

``runtime_stats`` counts *work* (dispatches, compiles); this module
counts *bytes*: every device buffer wrapped by an ``NDArray`` is
registered here (deduplicated by buffer identity, so views/aliases of
one buffer count once) and un-registered by a ``weakref.finalize``
callback when the buffer dies.  That yields live bytes / live count /
peak bytes / cumulative allocated, broken down per creating op and per
dtype — the in-process analog of a device memory profiler, with zero
change to array lifetimes (weak references only; sizes come from the
buffer's shape/dtype aval, never from a device read).

Cost model (PR 2's guard-first contract, pinned by
``tests/test_bench_gate.py``): tracking is OFF by default and every
hook site pays one dict read when it is off.  When ON, an allocation
costs a few dict increments plus one ``weakref.finalize`` registration;
when additionally the profiler is recording, each alloc/free emits a
chrome-trace counter ("C") event so traces show a live/peak-bytes
timeline alongside the dispatch spans (``docs/OBSERVABILITY.md``).

Attribution: the dispatch layer (``ndarray.imperative_invoke``) labels
output buffers with the creating op's canonical name via
:func:`set_origin`; creation helpers (``array``/``zeros``/...) label
themselves; anything else lands in the ``"<wrap>"`` bucket.

Concurrency: finalizers run on whatever thread triggers GC, while
``track`` runs on the dispatching thread — unlike ``runtime_stats``'
independent flat counters, the tables here are multi-field invariants
(live = allocated - freed, per-op rows must sum to totals), so every
mutation and every read happens under one module lock (``_lock``).
Lost increments would be *permanent* drift in the live/peak gauges,
not transient noise, which is why this tracker pays for the lock.

Environment: ``MXNET_TPU_MEMORY_TRACK=1`` enables tracking from import;
``MXNET_TPU_DIAG=<file>`` (the diagnostic-dump env, see
``runtime_stats``) enables it too so the dump's memory section is
populated in production runs.
"""

from __future__ import annotations

import os
import threading
import weakref

from . import profiler as _prof

__all__ = ["start", "stop", "reset", "is_enabled", "track", "set_origin",
           "snapshot", "emit_counter", "live_totals"]

# mxlint: disable=thread-shared-state -- single-key GIL-atomic enable flag; the guard-first contract forbids a lock on the disabled path
_state = {"on": False}

# guards _live/_totals/_per_op/_per_dtype below; leaf lock (nothing is
# acquired while held — trace events are emitted after release).
# RLock, not Lock: registering a weakref inside track() can trigger a
# GC cycle that runs _on_free on the SAME thread while the lock is
# held; the per-key decrements are arithmetically independent of the
# in-flight increments, so reentrancy is safe where deadlock is not.
_lock = threading.RLock()

# id(buffer) -> (nbytes, op, dtype, finalizer) for every live tracked
# buffer.  id() reuse is safe: the finalizer removes the entry before
# CPython can hand the address to a new object.
_live: dict = {}
_totals = {"live_bytes": 0, "live_count": 0, "peak_bytes": 0,
           "allocated_bytes": 0, "allocations": 0,
           "freed_bytes": 0, "frees": 0}
# op/dtype -> {"live_bytes", "live_count", "peak_bytes",
#              "allocated_bytes", "allocations"}
_per_op: dict = {}
_per_dtype: dict = {}

# creating-op label for the next tracked buffer(s); written by the
# dispatch layer (only while tracking is on) around output wrapping
_origin = [None]

_tracer_cls = []  # cached jax.core.Tracer, resolved on first track()


def is_enabled():
    return _state["on"]


def start():
    """Begin tracking buffers wrapped from now on (idempotent)."""
    _state["on"] = True


def stop():
    """Stop tracking new buffers.  Already-tracked buffers keep their
    finalizers, so live counts stay correct as they die."""
    _state["on"] = False


def set_origin(op):
    """Label subsequently tracked buffers with creating op ``op``;
    returns the previous label so callers can restore it."""
    prev = _origin[0]
    _origin[0] = op
    return prev


def _bucket(table, key):
    b = table.get(key)
    if b is None:
        b = table[key] = {"live_bytes": 0, "live_count": 0,
                          "peak_bytes": 0, "allocated_bytes": 0,
                          "allocations": 0}
    return b


def _is_concrete_device_array(buf):
    import jax

    if not _tracer_cls:
        _tracer_cls.append(jax.core.Tracer)
    return isinstance(buf, jax.Array) and not isinstance(buf,
                                                        _tracer_cls[0])


def track(buf, op=None):
    """Register one device buffer (no-op when disabled, deduplicated).

    Size comes from ``shape x dtype.itemsize`` — aval metadata, never a
    device read, so this is safe on async/undelivered arrays and keeps
    the compute path host-sync-free (mxlint).
    """
    if not _state["on"]:
        return
    key = id(buf)
    try:
        if not _is_concrete_device_array(buf):
            return  # tracers hold no HBM; host values aren't device mem
        nbytes = int(buf.size) * int(buf.dtype.itemsize)
        dtype = str(buf.dtype)
    except Exception:
        return  # abstract/exotic value: never let tracking break dispatch
    if op is None:
        op = _origin[0] or "<wrap>"
    with _lock:
        if key in _live:
            return  # alias/view of an already-tracked buffer
        fin = weakref.finalize(buf, _on_free, key, nbytes, op, dtype)
        fin.atexit = False  # accounting only; nothing to flush at exit
        _live[key] = (nbytes, op, dtype, fin)
        _totals["live_bytes"] += nbytes
        _totals["live_count"] += 1
        _totals["allocated_bytes"] += nbytes
        _totals["allocations"] += 1
        if _totals["live_bytes"] > _totals["peak_bytes"]:
            _totals["peak_bytes"] = _totals["live_bytes"]
        for table, k in ((_per_op, op), (_per_dtype, dtype)):
            b = _bucket(table, k)
            b["live_bytes"] += nbytes
            b["live_count"] += 1
            b["allocated_bytes"] += nbytes
            b["allocations"] += 1
            if b["live_bytes"] > b["peak_bytes"]:
                b["peak_bytes"] = b["live_bytes"]
        live, peak = _totals["live_bytes"], _totals["peak_bytes"]
    _emit(live, peak)


def _on_free(key, nbytes, op, dtype):
    with _lock:
        if _live.pop(key, None) is None:
            return  # reset() already dropped it
        _totals["live_bytes"] -= nbytes
        _totals["live_count"] -= 1
        _totals["freed_bytes"] += nbytes
        _totals["frees"] += 1
        for table, k in ((_per_op, op), (_per_dtype, dtype)):
            b = table.get(k)
            if b is not None:
                b["live_bytes"] -= nbytes
                b["live_count"] -= 1
        live, peak = _totals["live_bytes"], _totals["peak_bytes"]
    _emit(live, peak)


def _emit(live, peak):
    if not _prof._state["running"]:
        return
    _prof.add_event("device_memory", "memory", "C",
                    args={"live_bytes": live, "peak_bytes": peak})


def live_totals():
    """``(live_bytes, peak_bytes)`` read under the tracker lock — the
    accessor external gauges (serving metrics, health probe, metrics
    timeline) use instead of reaching into ``_totals`` directly."""
    with _lock:
        return _totals["live_bytes"], _totals["peak_bytes"]


def emit_counter():
    """Chrome-trace counter event of the current live/peak bytes (only
    while the profiler records).  Also called per step by the Gluon
    trainer/executor so traces keep a memory timeline even between
    allocations."""
    live, peak = live_totals()
    _emit(live, peak)


def snapshot(top=12):
    """Consistent copy of the tracker state: ``{"enabled", "totals",
    "per_op", "per_dtype"}``.  ``per_op``/``per_dtype`` keep the
    ``top`` rows by peak bytes (always all rows when ``top`` is None)."""

    def trim(table):
        items = sorted(table.items(),
                       key=lambda kv: -kv[1]["peak_bytes"])
        if top is not None:
            items = items[:top]
        return {k: dict(v) for k, v in items}

    with _lock:
        return {"enabled": _state["on"], "totals": dict(_totals),
                "per_op": trim(_per_op), "per_dtype": trim(_per_dtype)}


def reset():
    """Zero all accounting and detach every finalizer, so the tracker
    retains no references (weak or otherwise) to past buffers."""
    with _lock:
        for _nbytes, _op, _dtype, fin in list(_live.values()):
            fin.detach()
        _live.clear()
        for k in _totals:
            _totals[k] = 0
        _per_op.clear()
        _per_dtype.clear()
        _origin[0] = None


def _activate_from_env():
    if os.environ.get("MXNET_TPU_MEMORY_TRACK") == "1" \
            or os.environ.get("MXNET_TPU_DIAG"):
        start()
        return True
    return False


_activate_from_env()
