"""Observability autopilot — gated, audited reflexes that close the
doctor→action loop (the ROADMAP's "Observability autopilot" item).

PRs 8/10/15 built the *judge*: ranked perfdoctor findings, timeline
trend rules, x-ray shares, dead-shard heartbeats.  Every finding still
terminated at a human; production scale cannot page an operator per
shard.  This module is the *actuator*: a reflex engine evaluated
guard-first at the two seams where telemetry already flows — the
``Trainer.step`` tail (``on_step``, right after ``metrics_timeline``
samples, so the ring is fresh) and the serving accounting path
(``on_serve``) — which re-runs the cheap doctor rules over the live
state every ``MXNET_TPU_AUTOPILOT_INTERVAL`` evaluations and maps each
firing rule onto one bounded, reversible action:

====================  ==================  ==============================
trigger rule          reflex              armed action
====================  ==================  ==============================
timeline-leak         force-checkpoint    async ``CheckpointManager``
                                          snapshot now + projected-OOM
                                          warning (PR 6 manager)
recompile-storm       pin-bucket          install a registry bucket hint
                                          on the churned integer attr so
                                          the cache key ladder collapses
                                          (``ops.registry``)
timeline-kv-drift     restart-rank        park a ``restart_rank``
                                          request on PS shard 0; the
                                          ``tools/launch.py`` supervisor
                                          polls and relaunches (PR 9)
serve-queue-dominated serve-tune          nudge ``InferenceServer``
                                          knobs within bounds (workers
                                          up, max-wait up, queue down)
slo-fast-burn         slo-shed            bounded load-shed: shrink the
                                          queue bound toward its floor
                                          and add a worker, so the
                                          budget burn stops at the
                                          admission edge (``slo.py``)
first-nan             halt-after-         checkpoint, then raise
                      checkpoint          :class:`AutopilotHalt`
====================  ==================  ==============================

Safety model (every reflex, no exceptions):

- **off by default** — the whole engine is dead until
  ``MXNET_TPU_AUTOPILOT=1`` (or :func:`enable`); disabled cost is ONE
  dict read, pinned by ``test_bench_gate.py`` and proved statically by
  mxlint's guard-first pass.
- **per-reflex gate** — each reflex reads its own env
  (``MXNET_TPU_AUTOPILOT_CKPT`` / ``_BUCKET`` / ``_RESTART`` /
  ``_SERVE`` / ``_SLO`` / ``_HALT``): ``1`` arms the real action,
  ``0`` silences
  the reflex entirely, *unset* means **dry-run** — the safe default
  when the master switch is on: the reflex evaluates, logs the
  would-be action, and ledgers it, but acts on nothing.
- **hysteresis** — a per-reflex cooldown
  (``MXNET_TPU_AUTOPILOT_COOLDOWN`` seconds) and a per-run action cap
  (``MXNET_TPU_AUTOPILOT_MAX_ACTIONS``); suppressed firings are
  ledgered with the reason, so the audit trail shows restraint too.
- **append-only ledger** — every fired / dry-run / suppressed decision
  is recorded (rule, evidence snapshot, action, outcome) in a bounded
  deque that rides diag dumps as a top-level ``autopilot`` section,
  renders in ``runtime_stats.report()`` and ``tools/diagnose.py
  --autopilot``, and feeds the ``mxnet_tpu_autopilot_*`` Prometheus
  counters.

Thread model: ``on_step`` runs on the training thread only (its clock
is a lock-free single-writer dict); ``on_serve`` runs on serving
worker threads; the ledger, counters, and hysteresis maps are shared
across both and mutate only under the module ``_lock``.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from .base import MXNetError
from .log import get_logger

__all__ = ["enable", "disable", "is_enabled", "reset", "on_step",
           "on_serve", "ledger", "ledger_section", "snapshot",
           "AutopilotHalt", "REFLEXES", "GATE_ENVS"]

# one reflex per doctor rule; GATE_ENVS is the per-reflex arm switch
REFLEXES = ("force-checkpoint", "pin-bucket", "restart-rank",
            "serve-tune", "slo-shed", "halt-after-checkpoint")
GATE_ENVS = {
    "force-checkpoint": "MXNET_TPU_AUTOPILOT_CKPT",
    "pin-bucket": "MXNET_TPU_AUTOPILOT_BUCKET",
    "restart-rank": "MXNET_TPU_AUTOPILOT_RESTART",
    "serve-tune": "MXNET_TPU_AUTOPILOT_SERVE",
    "slo-shed": "MXNET_TPU_AUTOPILOT_SLO",
    "halt-after-checkpoint": "MXNET_TPU_AUTOPILOT_HALT",
}

INTERVAL_DEFAULT = 32       # evaluate every N on_step/on_serve ticks
COOLDOWN_DEFAULT = 60.0     # seconds between actions of one reflex
MAX_ACTIONS_DEFAULT = 4     # per reflex per run (reset() re-opens)
HBM_GB_DEFAULT = 16.0       # leak-projection budget (v4-lite HBM)
SERVE_MAX_WORKERS_DEFAULT = 8
SERVE_MAX_WAIT_MS_DEFAULT = 50.0
SERVE_MIN_QUEUE_DEFAULT = 64
LEDGER_CAP = 256            # append-only, oldest entries roll off

_state = {"on": False}
_cfg = {"interval": INTERVAL_DEFAULT, "cooldown": COOLDOWN_DEFAULT,
        "max_actions": MAX_ACTIONS_DEFAULT, "hbm_gb": HBM_GB_DEFAULT,
        "serve_max_workers": SERVE_MAX_WORKERS_DEFAULT,
        "serve_max_wait_ms": SERVE_MAX_WAIT_MS_DEFAULT,
        "serve_min_queue": SERVE_MIN_QUEUE_DEFAULT,
        "gates": {r: "dry_run" for r in REFLEXES}}

# ledger / counters / hysteresis: shared between the training thread
# and serving workers — mutate under _lock only
_lock = threading.Lock()
_LEDGER: collections.deque = collections.deque(maxlen=LEDGER_CAP)
_counts = {"evals": 0, "fired": 0, "dry_run": 0, "suppressed": 0}
_last_action: dict = {}     # reflex -> monotonic time of last action
_actions: dict = {}         # reflex -> actions taken this run
# single-writer clocks: on_step runs on the training thread only, so
# its tick is the GIL-atomic lock-free idiom; the serve tick is bumped
# from worker threads and lives under _lock
_train_clock = {"n": 0}
_serve_clock = {"n": 0}
_nan_memo = [None]          # first_nan step already reacted to

_logger_cache: list = []


def _logger():
    if not _logger_cache:
        _logger_cache.append(get_logger("mxnet_tpu.autopilot"))
    return _logger_cache[0]


class AutopilotHalt(MXNetError):
    """Raised out of ``Trainer.step`` by an ARMED halt-after-checkpoint
    reflex: the first non-finite value was observed, a checkpoint was
    submitted, and continuing would only burn accelerator time
    polluting every parameter."""


# ------------------------------------------------------------ lifecycle


def _env_float(name, default):
    try:
        return float(os.environ.get(name) or default)
    except (TypeError, ValueError):
        return float(default)


def _gate_from_env(reflex):
    """``1``/truthy arms, ``0`` silences, unset -> dry-run (the safe
    default: a master-switched autopilot narrates before it touches)."""
    raw = os.environ.get(GATE_ENVS[reflex])
    if raw is None or raw == "":
        return "dry_run"
    return "off" if raw == "0" else "armed"


def enable(interval=None, cooldown=None, max_actions=None, hbm_gb=None,
           gates=None):
    """Arm the reflex engine.  Explicit arguments win over the
    ``MXNET_TPU_AUTOPILOT_*`` envs; ``gates`` merges per-reflex mode
    overrides (``"armed"`` / ``"dry_run"`` / ``"off"``) over the
    env-derived defaults.  Returns the resolved config."""
    _cfg["interval"] = max(1, int(
        interval if interval is not None
        else _env_float("MXNET_TPU_AUTOPILOT_INTERVAL",
                        INTERVAL_DEFAULT)))
    _cfg["cooldown"] = max(0.0, float(
        cooldown if cooldown is not None
        else _env_float("MXNET_TPU_AUTOPILOT_COOLDOWN",
                        COOLDOWN_DEFAULT)))
    _cfg["max_actions"] = max(1, int(
        max_actions if max_actions is not None
        else _env_float("MXNET_TPU_AUTOPILOT_MAX_ACTIONS",
                        MAX_ACTIONS_DEFAULT)))
    _cfg["hbm_gb"] = float(
        hbm_gb if hbm_gb is not None
        else _env_float("MXNET_TPU_AUTOPILOT_HBM_GB", HBM_GB_DEFAULT))
    _cfg["serve_max_workers"] = max(1, int(_env_float(
        "MXNET_TPU_AUTOPILOT_SERVE_MAX_WORKERS",
        SERVE_MAX_WORKERS_DEFAULT)))
    _cfg["serve_max_wait_ms"] = _env_float(
        "MXNET_TPU_AUTOPILOT_SERVE_MAX_WAIT_MS",
        SERVE_MAX_WAIT_MS_DEFAULT)
    _cfg["serve_min_queue"] = max(1, int(_env_float(
        "MXNET_TPU_AUTOPILOT_SERVE_MIN_QUEUE",
        SERVE_MIN_QUEUE_DEFAULT)))
    g = {r: _gate_from_env(r) for r in REFLEXES}
    if gates:
        for r, mode in gates.items():
            if r not in GATE_ENVS:
                raise MXNetError("unknown autopilot reflex %r (have %s)"
                                 % (r, ", ".join(REFLEXES)))
            if mode not in ("armed", "dry_run", "off"):
                raise MXNetError("unknown gate mode %r for reflex %r"
                                 % (mode, r))
            g[r] = mode
    _cfg["gates"] = g
    _state["on"] = True
    return dict(_cfg)


def disable():
    """Stop evaluating (the ledger stays readable; ``reset`` drops it)."""
    _state["on"] = False


def is_enabled():
    return _state["on"]


def reset():
    """Drop the ledger, counters, clocks, and hysteresis (tests); the
    enabled flag and resolved config stay as-is."""
    with _lock:
        _LEDGER.clear()
        _counts.update({"evals": 0, "fired": 0, "dry_run": 0,
                        "suppressed": 0})
        _last_action.clear()
        _actions.clear()
        _serve_clock["n"] = 0
        _nan_memo[0] = None
    _train_clock["n"] = 0


def _activate_from_env():
    """``MXNET_TPU_AUTOPILOT=1`` at import arms the engine (telemetry
    must never kill a training job: failures warn and leave it off)."""
    raw = os.environ.get("MXNET_TPU_AUTOPILOT")
    if not raw or raw == "0":
        return
    try:
        enable()
    except Exception:
        _logger().warning(
            "MXNET_TPU_AUTOPILOT is set but autopilot.enable() failed "
            "— reflexes stay off", exc_info=True)


# ------------------------------------------------------------ the seams


def on_step(trainer=None):
    """Training-step seam, called by ``Trainer.step``'s telemetry tail
    AFTER ``metrics_timeline.on_step`` (so the live ring already holds
    this step's sample).  Callers guard on ``_state["on"]``; the
    re-check keeps a mid-step disable safe and is the entire disabled
    cost.  An ARMED halt-after-checkpoint reflex raises
    :class:`AutopilotHalt` through here; every other failure warns."""
    if not _state["on"]:
        return
    _train_clock["n"] += 1
    if _train_clock["n"] % _cfg["interval"]:
        return
    try:
        _evaluate_training(trainer, _train_clock["n"])
    except AutopilotHalt:
        raise
    except Exception:
        _logger().warning("autopilot training evaluation failed "
                          "(reflexes skipped this round)",
                          exc_info=True)


def on_serve(server):
    """Serving seam, called from ``InferenceServer._account_batch`` on
    worker threads after each batch's stats commit.  Same guard/interval
    contract as :func:`on_step`; the tick lives under ``_lock`` because
    several workers race it."""
    if not _state["on"]:
        return
    with _lock:
        _serve_clock["n"] += 1
        tick = _serve_clock["n"]
    if tick % _cfg["interval"] != 0:
        return
    try:
        _evaluate_serving(server, tick)
    except Exception:
        _logger().warning("autopilot serving evaluation failed "
                          "(reflexes skipped this round)",
                          exc_info=True)


# ----------------------------------------------------------- evaluation


def _count_eval():
    from . import runtime_stats as _rts

    with _lock:
        _counts["evals"] += 1
    _rts.inc("autopilot_evals")


def _evaluate_training(trainer, step):
    from . import metrics_timeline as _metrics
    from . import perfdoctor as _doctor

    _count_eval()
    samples = [s for s in _metrics.samples() if isinstance(s, dict)]
    for f in _doctor._check_leak(samples):
        _reflex_checkpoint(f, trainer, step, samples)
    for f in _doctor._check_kv_drift(samples, top=1):
        _reflex_restart(f, step)
    dump = _doctor.live_dump(serving=False)
    for f in _doctor._check_recompiles(dump):
        _reflex_bucket(f, step)
    _reflex_nan(trainer, step)


def _evaluate_serving(server, tick):
    from . import perfdoctor as _doctor

    _count_eval()
    dump = _doctor.live_dump()
    for f in _doctor._check_serving(dump):
        if f["rule"] == "serve-queue-dominated":
            _reflex_serve(f, server, tick)
    for f in _doctor._check_slo(dump):
        if f["rule"] == "slo-fast-burn":
            _reflex_slo(f, server, tick)


# -------------------------------------------------------------- reflexes


def _reflex_checkpoint(finding, trainer, step, samples):
    """timeline-leak -> force an async checkpoint before the projected
    exhaustion, and say WHEN that is (the warning a human can act on
    even when the gate stays dry)."""
    pts = [(s.get("step", i), s["live_bytes"])
           for i, s in enumerate(samples)
           if s.get("live_bytes") is not None]
    projected = None
    if len(pts) >= 2:
        from . import perfdoctor as _doctor

        slope = _doctor._lin_slope([p[0] for p in pts],
                                   [p[1] for p in pts])
        budget = _cfg["hbm_gb"] * (1 << 30)
        live = pts[-1][1]
        if slope > 0 and live < budget:
            projected = int(pts[-1][0] + (budget - live) / slope)
    action = ("force an async checkpoint now (CheckpointManager."
              "save_trainer) so the run can resume past the OOM")
    evidence = list(finding.get("evidence") or [])
    if projected is not None:
        action += " — projected %.0f GB HBM exhaustion ~ step %d" \
            % (_cfg["hbm_gb"], projected)
        evidence.append("projected exhaustion of the %.0f GB budget "
                        "(MXNET_TPU_AUTOPILOT_HBM_GB) ~ step %d"
                        % (_cfg["hbm_gb"], projected))

    def act():
        from . import checkpoint as _ckpt

        mgr = _ckpt.manager()
        if mgr is None:
            return {"saved": False,
                    "reason": "checkpointing disabled "
                              "(checkpoint.enable() first)"}
        if trainer is None:
            return {"saved": False,
                    "reason": "no trainer handle at the step seam"}
        mgr.save_trainer(trainer, step=step)
        return {"saved": True, "step": step}

    _consider("force-checkpoint", finding, step, act,
              action=action, evidence=evidence)


def _churned_int_attrs(op):
    """{attr: sorted values} for the integer (non-bool) attrs that vary
    across the op's recent storm cache keys — the dimensions a bucket
    hint can pin."""
    from . import runtime_stats as _rts

    st = _rts._STORM.get(op)
    if not st:
        return {}
    values: dict = {}
    for key in list(st.get("keys") or ()):
        pairs = _rts._attr_pairs(key)
        if not pairs:
            continue
        for attr, val in pairs:
            if isinstance(val, int) and not isinstance(val, bool):
                values.setdefault(attr, set()).add(val)
    return {a: sorted(vs) for a, vs in values.items() if len(vs) > 1}


def _pow2_ladder(maxv):
    """Power-of-two rungs 8..>=maxv — every distinct value collapses
    onto O(log) buckets instead of one cache entry each."""
    top = 8
    while top < maxv:
        top *= 2
    ladder, v = [], 8
    while v <= top:
        ladder.append(v)
        v *= 2
    return tuple(ladder)


def _reflex_bucket(finding, step):
    """recompile-storm -> install a registry bucket hint on the churned
    integer attr so later values pad up onto a small ladder and the
    storm STOPS (not just gets named).  Ops already hinted are skipped
    outright: storm counters are cumulative, so without this memo one
    storm would re-fire every evaluation forever."""
    from .ops import registry as _registry

    op = finding.get("anchor")
    if not op or op in _registry.bucket_hints():
        return
    churn = _churned_int_attrs(op)
    ladders = {a: _pow2_ladder(max(vs)) for a, vs in churn.items()}
    action = ("install pad-to-bucket hint(s) on %r: %s"
              % (op, ", ".join("%s -> ladder %s" % (a, ladders[a])
                               for a in sorted(ladders))
                 or "no churning integer attr identified — aval/shape "
                    "churn needs a source-side fix"))

    def act():
        installed = {}
        for attr, ladder in ladders.items():
            _registry.install_bucket_hint(op, attr, ladder)
            installed[attr] = list(ladder)
        if not installed:
            return {"op": op, "installed": {},
                    "reason": "no churning integer attr in the recent "
                              "cache keys (shape churn is not attr "
                              "churn)"}
        return {"op": op, "installed": installed}

    _consider("pin-bucket", finding, step, act, action=action)


def _reflex_restart(finding, step):
    """timeline-kv-drift -> park a ``restart_rank`` request on PS shard
    0; the ``tools/launch.py`` supervisor polls the head and relaunches
    this worker through the PR 9 supervise/auto-resume loop."""

    def act():
        from . import profiler as _prof

        kv = _prof._kvstore_handle
        if kv is None or not hasattr(kv, "request_restart"):
            return {"requested": False,
                    "reason": "no kvstore handle registered "
                              "(dist run required)"}
        rank = getattr(kv, "rank", None)
        ok = kv.request_restart(rank=rank, reason=finding["title"])
        return {"requested": bool(ok), "rank": rank}

    _consider("restart-rank", finding, step, act,
              action="request supervised relaunch of this worker "
                     "(restart_rank via PS shard 0; honored by "
                     "tools/launch.py --supervise)")


def _reflex_serve(finding, server, tick):
    """serve-queue-dominated -> nudge the live server's knobs within
    bounds: one more worker (toward SERVE_MAX_WORKERS), a longer batch
    window (x1.5 toward SERVE_MAX_WAIT_MS — fuller batches amortize
    dispatch), and a tighter queue bound (x0.75 toward SERVE_MIN_QUEUE
    — shed load earlier instead of queueing past the SLO)."""

    def act():
        if server is None:
            return {"adjusted": {},
                    "reason": "no server handle at the seam"}
        changed = {}
        w = int(server.num_workers)
        if w < _cfg["serve_max_workers"]:
            server.set_workers(w + 1)
            changed["workers"] = [w, w + 1]
        wait_ms = float(server.max_wait) * 1e3
        cap = float(_cfg["serve_max_wait_ms"])
        if wait_ms < cap:
            new = min(cap, max(wait_ms * 1.5, wait_ms + 0.5))
            server.set_max_wait_ms(new)
            changed["max_wait_ms"] = [round(wait_ms, 3), round(new, 3)]
        q = int(server.max_queue)
        floor = max(int(_cfg["serve_min_queue"]),
                    int(getattr(server, "max_bucket", 1)))
        if q > floor:
            new_q = max(floor, int(q * 0.75))
            if new_q < q:
                server.set_max_queue(new_q)
                changed["max_queue"] = [q, new_q]
        if not changed:
            return {"adjusted": {},
                    "reason": "every knob already at its bound"}
        return {"adjusted": changed}

    _consider("serve-tune", finding, tick, act,
              action="nudge serving knobs within bounds (workers up, "
                     "max-wait up, queue bound down)")


def _reflex_slo(finding, server, tick):
    """slo-fast-burn -> bounded load-shed at the admission edge:
    tighten the queue bound (x0.75 toward SERVE_MIN_QUEUE, so excess
    load turns into fast explicit rejections instead of slow
    over-threshold completions that burn the latency budget twice) and
    add a worker toward SERVE_MAX_WORKERS to raise drain rate.  Both
    knobs are reversible setters on the live server; ``_consider``
    supplies the dry-run default, cooldown, cap, and ledger."""

    def act():
        if server is None:
            return {"adjusted": {},
                    "reason": "no server handle at the seam"}
        changed = {}
        q = int(server.max_queue)
        floor = max(int(_cfg["serve_min_queue"]),
                    int(getattr(server, "max_bucket", 1)))
        if q > floor:
            new_q = max(floor, int(q * 0.75))
            if new_q < q:
                server.set_max_queue(new_q)
                changed["max_queue"] = [q, new_q]
        w = int(server.num_workers)
        if w < _cfg["serve_max_workers"]:
            server.set_workers(w + 1)
            changed["workers"] = [w, w + 1]
        if not changed:
            return {"adjusted": {},
                    "reason": "every knob already at its bound"}
        return {"adjusted": changed}

    _consider("slo-shed", finding, tick, act,
              action="shed load at the admission edge (queue bound "
                     "down toward floor, workers up toward cap)")


def _reflex_nan(trainer, step):
    """health first-NaN -> checkpoint the last finite state, then (when
    ARMED) raise :class:`AutopilotHalt`: every step past the first
    non-finite value only spreads it.  Once per incident — the memo
    keys on the recorded first_nan step."""
    from . import health as _health

    mon = _health.monitor()
    if mon is None:
        return
    fn = getattr(mon, "first_nan", None)
    if not fn:
        return
    if _nan_memo[0] == fn.get("step"):
        return
    _nan_memo[0] = fn.get("step")
    finding = {"rule": "first-nan", "score": 1.0, "severity": "warn",
               "title": "first non-finite value at step %s in %r"
                        % (fn.get("step"), fn.get("key")),
               "anchor": fn.get("key"),
               "evidence": ["first_nan: %r" % (fn,)],
               "action": "checkpoint the last finite state, then halt"}

    def act():
        from . import checkpoint as _ckpt

        mgr = _ckpt.manager()
        saved = False
        if mgr is not None and trainer is not None:
            mgr.save_trainer(trainer, step=step)
            saved = True
        raise AutopilotHalt(
            "autopilot: halting after first non-finite value "
            "(step %s, key %r)%s — inspect the flight dump / health "
            "snapshot, then resume from the checkpoint"
            % (fn.get("step"), fn.get("key"),
               "; checkpoint submitted" if saved
               else "; NO checkpoint (no manager/trainer)"))

    _consider("halt-after-checkpoint", finding, step, act,
              action="checkpoint last finite state, then halt the run")


# ------------------------------------------------------- gate + ledger


def _consider(reflex, finding, step, act, action=None, evidence=None):
    """The single decision point every reflex funnels through: gate
    mode, cooldown + max-actions hysteresis, the ledger append, and the
    Prometheus-visible counters.  ``act`` runs only when ARMED; an
    :class:`AutopilotHalt` it raises is ledgered, then re-raised."""
    from . import runtime_stats as _rts

    mode = _cfg["gates"].get(reflex, "dry_run")
    if mode == "off":
        return
    now = time.monotonic()
    entry = {"t": time.time(), "step": int(step),
             "rule": finding.get("rule"), "reflex": reflex,
             "severity": finding.get("severity"),
             "score": finding.get("score"),
             "action": action or finding.get("action"),
             "evidence": list(evidence if evidence is not None
                              else finding.get("evidence") or [])[:6]}
    cooldown, max_actions = _cfg["cooldown"], _cfg["max_actions"]
    with _lock:
        last = _last_action.get(reflex)
        if last is not None and now - last < cooldown:
            entry.update(mode="suppressed",
                         reason="cooldown (%.0fs of %.0fs left)"
                                % (cooldown - (now - last), cooldown))
            suppressed = True
        elif _actions.get(reflex, 0) >= max_actions:
            entry.update(mode="suppressed",
                         reason="max-actions cap (%d) reached this run"
                                % max_actions)
            suppressed = True
        else:
            _last_action[reflex] = now
            _actions[reflex] = _actions.get(reflex, 0) + 1
            suppressed = False
        if suppressed:
            _LEDGER.append(entry)
            _counts["suppressed"] += 1
    if suppressed:
        _rts.inc("autopilot_suppressed")
        return
    if mode == "dry_run":
        entry.update(mode="dry_run",
                     reason="gate %s unset — dry-run default"
                            % GATE_ENVS[reflex])
        with _lock:
            _LEDGER.append(entry)
            _counts["dry_run"] += 1
        _rts.inc("autopilot_dry_run")
        _logger().warning(
            "autopilot[dry-run] %s: %s — would: %s (set %s=1 to act, "
            "=0 to silence)", reflex, finding.get("title"),
            entry["action"], GATE_ENVS[reflex])
        return
    halt = None
    try:
        outcome = act()
    except AutopilotHalt as e:
        halt = e
        outcome = {"halt": str(e)}
    except Exception as e:  # an action must never crash the seam
        outcome = {"error": "%s: %s" % (type(e).__name__, e)}
    entry.update(mode="fired", outcome=outcome)
    with _lock:
        _LEDGER.append(entry)
        _counts["fired"] += 1
    _rts.inc("autopilot_fired")
    _logger().warning("autopilot[fired] %s: %s — %s -> %r",
                      reflex, finding.get("title"), entry["action"],
                      outcome)
    if halt is not None:
        raise halt


def ledger():
    """The append-only action ledger, oldest first (bounded at
    ``LEDGER_CAP``; older entries roll off)."""
    with _lock:
        return [dict(e) for e in _LEDGER]


def ledger_section():
    """The ``autopilot`` section diag dumps embed and ``report()`` /
    ``diagnose.py --autopilot`` render: config, decision counters, and
    the full ledger."""
    # config/state are single-writer dicts read lock-free everywhere
    # (the guard-first convention); only the ledger and its counters
    # are multi-writer and need the lock
    out = {"enabled": _state["on"],
           "interval": _cfg["interval"],
           "cooldown_s": _cfg["cooldown"],
           "max_actions": _cfg["max_actions"],
           "gates": dict(_cfg["gates"])}
    with _lock:
        out["counters"] = dict(_counts)
        out["entries"] = [dict(e) for e in _LEDGER]
    return out


def snapshot():
    """Alias of :func:`ledger_section` (the module-surface convention
    the other telemetry layers follow)."""
    return ledger_section()
