// mxtpu-cpp — header-only C++ API over the libmxtpu C ABI.
//
// Reference: cpp-package/ (C++ bindings generated over include/mxnet/
// c_api.h + c_predict_api.h).  TPU-native form: the tensor/compute API
// lives in Python/jax (XLA is the runtime); what C++ consumers need is
// the deployment predictor, the host dependency engine, and RecordIO —
// exactly the libmxtpu surface, wrapped here with RAII + exceptions.
//
// Build: no dependencies beyond libmxtpu.so:
//   g++ -std=c++17 app.cc -I cpp-package/include \
//       -I mxnet_tpu/native/include -L mxnet_tpu/native \
//       -lmxtpu -Wl,-rpath,mxnet_tpu/native
// For Predictor in a non-Python process, set MXTPU_PYTHONPATH (see
// native/src/predict.cc).
#ifndef MXTPU_CPP_MXTPU_HPP_
#define MXTPU_CPP_MXTPU_HPP_

#include <cstdint>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

// Real ABI headers (compiler-enforced consistency with libmxtpu).
#include <mxtpu/c_api.h>
#include <mxtpu/c_predict_api.h>

namespace mxtpu {
namespace cpp {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& msg) : std::runtime_error(msg) {}
};

inline void Check(int rc) {
  if (rc != 0) throw Error(MXTPUGetLastError());
}

enum class Device : int { kCPU = 1, kTPU = 2 };

// ------------------------------------------------------------- Predictor --
// Loads an exported model (symbol JSON + params blob) and runs forward
// passes.  Mirrors cpp-package's Predictor idiom over c_predict_api.h.
class Predictor {
  // CSR-flattened {name: shape} map for the C ABI's (keys, indptr, data)
  // convention.
  struct Shapes {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, sdata;
    explicit Shapes(
        const std::map<std::string, std::vector<uint32_t>>& input_shapes) {
      for (const auto& kv : input_shapes) {
        keys.push_back(kv.first.c_str());
        sdata.insert(sdata.end(), kv.second.begin(), kv.second.end());
        indptr.push_back(static_cast<uint32_t>(sdata.size()));
      }
    }
    uint32_t n() const { return static_cast<uint32_t>(keys.size()); }
  };

 public:
  Predictor(const std::string& symbol_json, const std::string& param_bytes,
            const std::map<std::string, std::vector<uint32_t>>& input_shapes,
            Device dev = Device::kCPU, int dev_id = 0) {
    Shapes s(input_shapes);
    Check(MXTPUPredCreate(symbol_json.c_str(), param_bytes.data(),
                          param_bytes.size(), static_cast<int>(dev), dev_id,
                          s.n(), s.keys.data(), s.indptr.data(),
                          s.sdata.data(), &handle_));
  }
  ~Predictor() {
    if (handle_) MXTPUPredFree(handle_);
  }
  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  Predictor(Predictor&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }

  void SetInput(const std::string& key, const float* data, uint64_t size) {
    Check(MXTPUPredSetInput(handle_, key.c_str(), data, size));
  }
  void SetInput(const std::string& key, const std::vector<float>& data) {
    SetInput(key, data.data(), data.size());
  }
  void Forward() { Check(MXTPUPredForward(handle_)); }

  std::vector<uint32_t> GetOutputShape(uint32_t index) const {
    const uint32_t* dims = nullptr;
    uint32_t ndim = 0;
    Check(MXTPUPredGetOutputShape(handle_, index, &dims, &ndim));
    return std::vector<uint32_t>(dims, dims + ndim);
  }
  std::vector<float> GetOutput(uint32_t index) const {
    auto shape = GetOutputShape(index);
    uint64_t n = 1;
    for (uint32_t d : shape) n *= d;
    std::vector<float> out(n);
    Check(MXTPUPredGetOutput(handle_, index, out.data(), n));
    return out;
  }
  // New predictor over the same weights with different input shapes.
  Predictor Reshape(
      const std::map<std::string, std::vector<uint32_t>>& input_shapes) {
    Shapes s(input_shapes);
    void* nh = nullptr;
    Check(MXTPUPredReshape(s.n(), s.keys.data(), s.indptr.data(),
                           s.sdata.data(), handle_, &nh));
    return Predictor(nh);
  }

 private:
  explicit Predictor(void* h) : handle_(h) {}
  void* handle_ = nullptr;
};

// --------------------------------------------------------------- Engine --
// Host-side async dependency engine (reference: include/mxnet/engine.h).
class Engine {
 public:
  explicit Engine(int n_workers = 4, int io_workers = 1) {
    Check(MXTPUEngineCreate(n_workers, io_workers, &handle_));
  }
  ~Engine() {
    if (handle_) MXTPUEngineFree(handle_);
  }
  Engine(const Engine&) = delete;
  Engine& operator=(const Engine&) = delete;

  uint64_t NewVariable() {
    uint64_t v = 0;
    Check(MXTPUEngineNewVar(handle_, &v));
    return v;
  }
  void DeleteVariable(uint64_t var) { Check(MXTPUEngineDelVar(handle_, var)); }
  // fn runs on a worker thread; nonzero return marks the op failed and the
  // error propagates to the next WaitForVar on its mutated vars.
  uint64_t Push(MXTPUEngineOpFn fn, void* ctx,
                const std::vector<uint64_t>& const_vars,
                const std::vector<uint64_t>& mutable_vars,
                const std::string& name = "", int property = 0) {
    uint64_t op_id = 0;
    Check(MXTPUEnginePush(handle_, fn, ctx, const_vars.data(),
                          static_cast<int>(const_vars.size()),
                          mutable_vars.data(),
                          static_cast<int>(mutable_vars.size()), property,
                          name.c_str(), &op_id));
    return op_id;
  }
  void OnComplete(uint64_t op_id) {
    Check(MXTPUEngineOnComplete(handle_, op_id));
  }
  void WaitForVar(uint64_t var) { Check(MXTPUEngineWaitForVar(handle_, var)); }
  void WaitAll() { Check(MXTPUEngineWaitAll(handle_)); }

 private:
  void* handle_ = nullptr;
};

// -------------------------------------------------------------- RecordIO --
class RecordReader {
 public:
  explicit RecordReader(const std::string& path, uint64_t chunk = 1 << 20,
                        int part = 0, int nparts = 1) {
    Check(MXTPURecordReaderCreate(path.c_str(), chunk, part, nparts,
                                  &handle_));
  }
  ~RecordReader() {
    if (handle_) MXTPURecordReaderFree(handle_);
  }
  RecordReader(const RecordReader&) = delete;
  RecordReader& operator=(const RecordReader&) = delete;

  // False at end of stream; the view is valid until the next call.
  bool Next(std::string* out) {
    const uint8_t* data = nullptr;
    uint32_t size = 0;
    Check(MXTPURecordReaderNext(handle_, &data, &size));
    if (!data) return false;
    out->assign(reinterpret_cast<const char*>(data), size);
    return true;
  }
  void Reset() { Check(MXTPURecordReaderReset(handle_)); }

 private:
  void* handle_ = nullptr;
};

class RecordWriter {
 public:
  explicit RecordWriter(const std::string& path) {
    Check(MXTPURecordWriterCreate(path.c_str(), &handle_));
  }
  ~RecordWriter() {
    if (handle_) MXTPURecordWriterFree(handle_);
  }
  RecordWriter(const RecordWriter&) = delete;
  RecordWriter& operator=(const RecordWriter&) = delete;

  uint64_t Write(const std::string& record) {
    uint64_t pos = 0;
    Check(MXTPURecordWriterWrite(
        handle_, reinterpret_cast<const uint8_t*>(record.data()),
        static_cast<uint32_t>(record.size()), &pos));
    return pos;
  }

 private:
  void* handle_ = nullptr;
};

// ================= training-capable tensor API (r4) ======================
// NDArray / Symbol / Executor / KVStore with RAII + exceptions over the
// full tensor C ABI — the same classes the reference's cpp-package
// builds over include/mxnet/c_api.h (mxnet-cpp/{ndarray,symbol,
// executor,kvstore}.h).  Training from pure C++ with zero Python source
// is exercised by cpp-package/example/train_cpp.cc.

class Context {
 public:
  explicit Context(Device dev = Device::kCPU, int id = 0)
      : dev_(static_cast<int>(dev)), id_(id) {}
  int dev_type() const { return dev_; }
  int dev_id() const { return id_; }

 private:
  int dev_;
  int id_;
};

class NDArray {
 public:
  NDArray() = default;
  NDArray(const std::vector<uint32_t>& shape, const Context& ctx = Context(),
          int dtype = 0) {
    Check(MXTPUNDArrayCreateEx(shape.data(),
                               static_cast<uint32_t>(shape.size()),
                               ctx.dev_type(), ctx.dev_id(), 0, dtype,
                               &handle_));
  }
  NDArray(const std::vector<uint32_t>& shape, const std::vector<float>& vals,
          const Context& ctx = Context())
      : NDArray(shape, ctx, 0) {
    SyncCopyFromCPU(vals);
  }
  // Adopt a handle minted by the C ABI (e.g. SimpleBind outputs).
  static NDArray Own(MXTPUHandle h) {
    NDArray a;
    a.handle_ = h;
    return a;
  }
  ~NDArray() { reset(); }
  NDArray(NDArray&& o) noexcept : handle_(o.handle_) { o.handle_ = 0; }
  NDArray& operator=(NDArray&& o) noexcept {
    if (this != &o) {
      reset();
      handle_ = o.handle_;
      o.handle_ = 0;
    }
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;

  MXTPUHandle handle() const { return handle_; }
  bool empty() const { return handle_ == 0; }

  std::vector<uint32_t> Shape() const {
    uint32_t ndim = 0;
    const uint32_t* data = nullptr;
    Check(MXTPUNDArrayGetShape(handle_, &ndim, &data));
    return std::vector<uint32_t>(data, data + ndim);
  }
  uint64_t Size() const {
    uint64_t n = 1;
    for (uint32_t d : Shape()) n *= d;
    return n;
  }
  void SyncCopyFromCPU(const std::vector<float>& vals) {
    Check(MXTPUNDArraySyncCopyFromCPU(handle_, vals.data(), vals.size()));
  }
  std::vector<float> SyncCopyToCPU() const {
    std::vector<float> out(Size());
    Check(MXTPUNDArraySyncCopyToCPU(handle_, out.data(), out.size()));
    return out;
  }
  NDArray Slice(uint32_t begin, uint32_t end) const {
    MXTPUHandle h = 0;
    Check(MXTPUNDArraySlice(handle_, begin, end, &h));
    return Own(h);
  }
  NDArray Reshape(const std::vector<int>& dims) const {
    MXTPUHandle h = 0;
    Check(MXTPUNDArrayReshape(handle_, static_cast<int>(dims.size()),
                              dims.data(), &h));
    return Own(h);
  }
  void WaitToRead() const { Check(MXTPUNDArrayWaitToRead(handle_)); }

  static void Save(const std::string& fname,
                   const std::map<std::string, NDArray*>& arrays) {
    std::vector<MXTPUHandle> hs;
    std::vector<const char*> keys;
    for (const auto& kv : arrays) {
      keys.push_back(kv.first.c_str());
      hs.push_back(kv.second->handle());
    }
    Check(MXTPUNDArraySave(fname.c_str(),
                           static_cast<uint32_t>(hs.size()), hs.data(),
                           keys.data()));
  }
  static std::map<std::string, NDArray> Load(const std::string& fname) {
    uint32_t n = 0, n_names = 0;
    MXTPUHandle* hs = nullptr;
    const char** names = nullptr;
    Check(MXTPUNDArrayLoad(fname.c_str(), &n, &hs, &n_names, &names));
    std::map<std::string, NDArray> out;
    for (uint32_t i = 0; i < n; ++i)
      out[n_names == n ? names[i] : std::to_string(i)] = Own(hs[i]);
    return out;
  }

 private:
  void reset() {
    if (handle_) MXTPUNDArrayFree(handle_);
    handle_ = 0;
  }
  MXTPUHandle handle_ = 0;
};

// Invoke a registered operator imperatively: Op("broadcast_add")(a, b).
class Op {
  // shared marshalling for both invoke forms
  struct Call {
    std::vector<MXTPUHandle> in;
    std::vector<const char*> keys, vals;
    Call(const std::vector<const NDArray*>& inputs,
         const std::map<std::string, std::string>& params) {
      for (const NDArray* a : inputs) in.push_back(a->handle());
      for (const auto& kv : params) {
        keys.push_back(kv.first.c_str());
        vals.push_back(kv.second.c_str());
      }
    }
  };

  void Run(Call& c, int* n_out, MXTPUHandle** outs) const {
    Check(MXTPUImperativeInvoke(handle_, static_cast<int>(c.in.size()),
                                c.in.data(), n_out, outs,
                                static_cast<int>(c.keys.size()),
                                c.keys.data(), c.vals.data()));
  }

 public:
  explicit Op(const std::string& name) {
    Check(MXTPUGetOpHandle(name.c_str(), &handle_));
  }
  std::vector<NDArray> operator()(
      const std::vector<const NDArray*>& inputs,
      const std::map<std::string, std::string>& params = {}) const {
    Call c(inputs, params);
    int n_out = 0;
    MXTPUHandle* outs = nullptr;
    Run(c, &n_out, &outs);
    std::vector<NDArray> result;
    for (int i = 0; i < n_out; ++i) result.push_back(NDArray::Own(outs[i]));
    return result;
  }
  // In-place update form: outputs written into existing arrays
  // (optimizer updates: sgd_update(w, g) -> w).
  void Invoke(const std::vector<const NDArray*>& inputs,
              const std::vector<NDArray*>& outputs,
              const std::map<std::string, std::string>& params = {}) const {
    Call c(inputs, params);
    std::vector<MXTPUHandle> out;
    for (NDArray* a : outputs) out.push_back(a->handle());
    int n_out = static_cast<int>(out.size());
    MXTPUHandle* outs = out.data();
    Run(c, &n_out, &outs);
  }

 private:
  MXTPUHandle handle_ = 0;
};

class Executor;

class Symbol {
 public:
  Symbol() = default;
  static Symbol Variable(const std::string& name) {
    MXTPUHandle h = 0;
    Check(MXTPUSymbolCreateVariable(name.c_str(), &h));
    return Own(h);
  }
  // One-step atomic-create + compose (the reference cpp-package's
  // generated per-op constructors reduce to exactly this).
  static Symbol CreateOp(const std::string& op_name, const std::string& name,
                         const std::map<std::string, Symbol*>& inputs,
                         const std::map<std::string, std::string>& params) {
    MXTPUHandle creator = 0;
    Check(MXTPUGetOpHandle(op_name.c_str(), &creator));
    std::vector<const char*> pkeys, pvals;
    for (const auto& kv : params) {
      pkeys.push_back(kv.first.c_str());
      pvals.push_back(kv.second.c_str());
    }
    MXTPUHandle h = 0;
    Check(MXTPUSymbolCreateAtomicSymbol(
        creator, static_cast<uint32_t>(pkeys.size()), pkeys.data(),
        pvals.data(), &h));
    std::vector<const char*> ikeys;
    std::vector<MXTPUHandle> iargs;
    for (const auto& kv : inputs) {
      ikeys.push_back(kv.first.c_str());
      iargs.push_back(kv.second->handle());
    }
    Check(MXTPUSymbolCompose(h, name.c_str(),
                             static_cast<uint32_t>(ikeys.size()),
                             ikeys.data(), iargs.data()));
    return Own(h);
  }
  static Symbol FromJSON(const std::string& json) {
    MXTPUHandle h = 0;
    Check(MXTPUSymbolCreateFromJSON(json.c_str(), &h));
    return Own(h);
  }
  static Symbol Own(MXTPUHandle h) {
    Symbol s;
    s.handle_ = h;
    return s;
  }
  ~Symbol() {
    if (handle_) MXTPUSymbolFree(handle_);
  }
  Symbol(Symbol&& o) noexcept : handle_(o.handle_) { o.handle_ = 0; }
  Symbol& operator=(Symbol&& o) noexcept {
    if (this != &o) {
      if (handle_) MXTPUSymbolFree(handle_);
      handle_ = o.handle_;
      o.handle_ = 0;
    }
    return *this;
  }
  Symbol(const Symbol&) = delete;
  Symbol& operator=(const Symbol&) = delete;

  MXTPUHandle handle() const { return handle_; }
  std::string ToJSON() const {
    const char* json = nullptr;
    Check(MXTPUSymbolSaveToJSON(handle_, &json));
    return json;
  }
  std::vector<std::string> ListArguments() const {
    return StrList(&MXTPUSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return StrList(&MXTPUSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return StrList(&MXTPUSymbolListAuxiliaryStates);
  }
  // Per-argument gradient requests ("write"/"add"/"null"); arguments
  // absent from the map default to "write" (reference: cpp-package
  // Symbol::SimpleBind grad_req_type map).
  inline Executor SimpleBind(
      const Context& ctx,
      const std::map<std::string, std::vector<uint32_t>>& arg_shapes,
      const std::map<std::string, std::string>& grad_req_map) const;
  inline Executor SimpleBind(
      const Context& ctx,
      const std::map<std::string, std::vector<uint32_t>>& arg_shapes,
      const std::string& grad_req = "write") const;

 private:
  using ListFn = int (*)(MXTPUHandle, uint32_t*, const char***);
  std::vector<std::string> StrList(ListFn fn) const {
    uint32_t n = 0;
    const char** arr = nullptr;
    Check(fn(handle_, &n, &arr));
    return std::vector<std::string>(arr, arr + n);
  }
  MXTPUHandle handle_ = 0;
};

class Executor {
 public:
  ~Executor() {
    if (handle_) MXTPUExecutorFree(handle_);
  }
  Executor(Executor&& o) noexcept
      : handle_(o.handle_), arg_arrays(std::move(o.arg_arrays)),
        grad_arrays(std::move(o.grad_arrays)),
        aux_arrays(std::move(o.aux_arrays)) {
    o.handle_ = 0;
  }
  Executor(const Executor&) = delete;
  Executor& operator=(const Executor&) = delete;

  void Forward(bool is_train) {
    Check(MXTPUExecutorForward(handle_, is_train ? 1 : 0));
  }
  void Backward(const std::vector<const NDArray*>& head_grads = {}) {
    std::vector<MXTPUHandle> hs;
    for (const NDArray* a : head_grads) hs.push_back(a->handle());
    Check(MXTPUExecutorBackward(handle_,
                                static_cast<uint32_t>(hs.size()),
                                hs.empty() ? nullptr : hs.data()));
  }
  std::vector<NDArray> Outputs() const {
    uint32_t n = 0;
    MXTPUHandle* hs = nullptr;
    Check(MXTPUExecutorOutputs(handle_, &n, &hs));
    std::vector<NDArray> out;
    for (uint32_t i = 0; i < n; ++i) out.push_back(NDArray::Own(hs[i]));
    return out;
  }

  std::vector<NDArray> arg_arrays;   // bound parameter/input buffers
  std::vector<NDArray> grad_arrays; // empty() where grad_req is null
  std::vector<NDArray> aux_arrays;

 private:
  friend class Symbol;
  Executor() = default;
  MXTPUHandle handle_ = 0;
};

inline Executor Symbol::SimpleBind(
    const Context& ctx,
    const std::map<std::string, std::vector<uint32_t>>& arg_shapes,
    const std::string& grad_req) const {
  return SimpleBind(ctx, arg_shapes,
                    std::map<std::string, std::string>{{"*", grad_req}});
}

inline Executor Symbol::SimpleBind(
    const Context& ctx,
    const std::map<std::string, std::vector<uint32_t>>& arg_shapes,
    const std::map<std::string, std::string>& grad_req_map) const {
  std::vector<const char*> names;
  std::vector<uint32_t> idx{0}, data;
  for (const auto& kv : arg_shapes) {
    names.push_back(kv.first.c_str());
    data.insert(data.end(), kv.second.begin(), kv.second.end());
    idx.push_back(static_cast<uint32_t>(data.size()));
  }
  std::vector<std::string> arg_names = ListArguments();
  auto star = grad_req_map.find("*");
  const std::string fallback =
      star != grad_req_map.end() ? star->second : std::string("write");
  std::vector<std::string> req_store;
  for (const std::string& n : arg_names) {
    auto it = grad_req_map.find(n);
    req_store.push_back(it != grad_req_map.end() ? it->second : fallback);
  }
  std::vector<const char*> req_names;
  std::vector<const char*> req_types;
  for (const std::string& n : arg_names) req_names.push_back(n.c_str());
  for (const std::string& r : req_store) req_types.push_back(r.c_str());
  uint32_t num_in = 0, num_aux = 0;
  MXTPUHandle* in_arr = nullptr;
  MXTPUHandle* grad_arr = nullptr;
  MXTPUHandle* aux_arr = nullptr;
  Executor ex;
  Check(MXTPUExecutorSimpleBind(
      handle_, ctx.dev_type(), ctx.dev_id(), 0, nullptr, nullptr, nullptr,
      static_cast<uint32_t>(req_names.size()), req_names.data(),
      req_types.data(), static_cast<uint32_t>(names.size()), names.data(),
      data.data(), idx.data(), 0, nullptr, nullptr, 0, nullptr, nullptr, 0,
      nullptr, nullptr, nullptr, nullptr, nullptr, nullptr, &num_in, &in_arr,
      &grad_arr, &num_aux, &aux_arr, 0, &ex.handle_));
  for (uint32_t i = 0; i < num_in; ++i)
    ex.arg_arrays.push_back(NDArray::Own(in_arr[i]));
  for (uint32_t i = 0; i < num_in; ++i)
    ex.grad_arrays.push_back(grad_arr[i] ? NDArray::Own(grad_arr[i])
                                         : NDArray());
  for (uint32_t i = 0; i < num_aux; ++i)
    ex.aux_arrays.push_back(NDArray::Own(aux_arr[i]));
  return ex;
}

class KVStore {
 public:
  explicit KVStore(const std::string& type = "local") {
    Check(MXTPUKVStoreCreate(type.c_str(), &handle_));
  }
  ~KVStore() {
    if (handle_) MXTPUKVStoreFree(handle_);
  }
  KVStore(const KVStore&) = delete;
  KVStore& operator=(const KVStore&) = delete;

  void Init(int key, const NDArray& val) {
    MXTPUHandle h = val.handle();
    Check(MXTPUKVStoreInit(handle_, 1, &key, &h));
  }
  void Push(int key, const NDArray& val, int priority = 0) {
    MXTPUHandle h = val.handle();
    Check(MXTPUKVStorePush(handle_, 1, &key, &h, priority));
  }
  void Pull(int key, NDArray* out, int priority = 0) {
    MXTPUHandle h = out->handle();
    Check(MXTPUKVStorePull(handle_, 1, &key, &h, priority));
  }

 private:
  MXTPUHandle handle_ = 0;
};

}  // namespace cpp
}  // namespace mxtpu

#endif  // MXTPU_CPP_MXTPU_HPP_
