// mxtpu-cpp — header-only C++ API over the libmxtpu C ABI.
//
// Reference: cpp-package/ (C++ bindings generated over include/mxnet/
// c_api.h + c_predict_api.h).  TPU-native form: the tensor/compute API
// lives in Python/jax (XLA is the runtime); what C++ consumers need is
// the deployment predictor, the host dependency engine, and RecordIO —
// exactly the libmxtpu surface, wrapped here with RAII + exceptions.
//
// Build: no dependencies beyond libmxtpu.so:
//   g++ -std=c++17 app.cc -I cpp-package/include -L mxnet_tpu/native \
//       -lmxtpu -Wl,-rpath,mxnet_tpu/native
// For Predictor in a non-Python process, set MXTPU_PYTHONPATH (see
// native/src/predict.cc).
#ifndef MXTPU_CPP_MXTPU_HPP_
#define MXTPU_CPP_MXTPU_HPP_

#include <cstdint>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

extern "C" {
const char* MXTPUGetLastError(void);
int MXTPUEngineCreate(int n_workers, int io_workers, void** out);
int MXTPUEngineFree(void* h);
int MXTPUEngineNewVar(void* h, uint64_t* out);
int MXTPUEngineDelVar(void* h, uint64_t var);
typedef int (*MXTPUEngineOpFn)(void* ctx, uint64_t op_id);
int MXTPUEnginePush(void* h, MXTPUEngineOpFn fn, void* ctx,
                    const uint64_t* cvars, int ncv, const uint64_t* mvars,
                    int nmv, int prop, const char* name, uint64_t* out_op_id);
int MXTPUEngineOnComplete(void* h, uint64_t op_id);
int MXTPUEngineOnCompleteError(void* h, uint64_t op_id, const char* msg);
int MXTPUEngineWaitForVar(void* h, uint64_t var);
int MXTPUEngineWaitAll(void* h);
int MXTPURecordReaderCreate(const char* path, uint64_t chunk, int part,
                            int nparts, void** out);
int MXTPURecordReaderNext(void* h, const uint8_t** data, uint32_t* size);
int MXTPURecordReaderReset(void* h);
int MXTPURecordReaderFree(void* h);
int MXTPURecordWriterCreate(const char* path, void** out);
int MXTPURecordWriterWrite(void* h, const uint8_t* data, uint32_t size,
                           uint64_t* out_pos);
int MXTPURecordWriterFree(void* h);
int MXTPUPredCreate(const char* symbol_json, const void* param_bytes,
                    uint64_t param_size, int dev_type, int dev_id,
                    uint32_t num_input_nodes, const char** input_keys,
                    const uint32_t* input_shape_indptr,
                    const uint32_t* input_shape_data, void** out);
int MXTPUPredSetInput(void* h, const char* key, const float* data,
                      uint64_t size);
int MXTPUPredForward(void* h);
int MXTPUPredGetOutputShape(void* h, uint32_t index,
                            const uint32_t** shape_data, uint32_t* shape_ndim);
int MXTPUPredGetOutput(void* h, uint32_t index, float* data, uint64_t size);
int MXTPUPredReshape(uint32_t num_input_nodes, const char** input_keys,
                     const uint32_t* input_shape_indptr,
                     const uint32_t* input_shape_data, void* h, void** out);
int MXTPUPredFree(void* h);
}

namespace mxtpu {
namespace cpp {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& msg) : std::runtime_error(msg) {}
};

inline void Check(int rc) {
  if (rc != 0) throw Error(MXTPUGetLastError());
}

enum class Device : int { kCPU = 1, kTPU = 2 };

// ------------------------------------------------------------- Predictor --
// Loads an exported model (symbol JSON + params blob) and runs forward
// passes.  Mirrors cpp-package's Predictor idiom over c_predict_api.h.
class Predictor {
  // CSR-flattened {name: shape} map for the C ABI's (keys, indptr, data)
  // convention.
  struct Shapes {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, sdata;
    explicit Shapes(
        const std::map<std::string, std::vector<uint32_t>>& input_shapes) {
      for (const auto& kv : input_shapes) {
        keys.push_back(kv.first.c_str());
        sdata.insert(sdata.end(), kv.second.begin(), kv.second.end());
        indptr.push_back(static_cast<uint32_t>(sdata.size()));
      }
    }
    uint32_t n() const { return static_cast<uint32_t>(keys.size()); }
  };

 public:
  Predictor(const std::string& symbol_json, const std::string& param_bytes,
            const std::map<std::string, std::vector<uint32_t>>& input_shapes,
            Device dev = Device::kCPU, int dev_id = 0) {
    Shapes s(input_shapes);
    Check(MXTPUPredCreate(symbol_json.c_str(), param_bytes.data(),
                          param_bytes.size(), static_cast<int>(dev), dev_id,
                          s.n(), s.keys.data(), s.indptr.data(),
                          s.sdata.data(), &handle_));
  }
  ~Predictor() {
    if (handle_) MXTPUPredFree(handle_);
  }
  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  Predictor(Predictor&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }

  void SetInput(const std::string& key, const float* data, uint64_t size) {
    Check(MXTPUPredSetInput(handle_, key.c_str(), data, size));
  }
  void SetInput(const std::string& key, const std::vector<float>& data) {
    SetInput(key, data.data(), data.size());
  }
  void Forward() { Check(MXTPUPredForward(handle_)); }

  std::vector<uint32_t> GetOutputShape(uint32_t index) const {
    const uint32_t* dims = nullptr;
    uint32_t ndim = 0;
    Check(MXTPUPredGetOutputShape(handle_, index, &dims, &ndim));
    return std::vector<uint32_t>(dims, dims + ndim);
  }
  std::vector<float> GetOutput(uint32_t index) const {
    auto shape = GetOutputShape(index);
    uint64_t n = 1;
    for (uint32_t d : shape) n *= d;
    std::vector<float> out(n);
    Check(MXTPUPredGetOutput(handle_, index, out.data(), n));
    return out;
  }
  // New predictor over the same weights with different input shapes.
  Predictor Reshape(
      const std::map<std::string, std::vector<uint32_t>>& input_shapes) {
    Shapes s(input_shapes);
    void* nh = nullptr;
    Check(MXTPUPredReshape(s.n(), s.keys.data(), s.indptr.data(),
                           s.sdata.data(), handle_, &nh));
    return Predictor(nh);
  }

 private:
  explicit Predictor(void* h) : handle_(h) {}
  void* handle_ = nullptr;
};

// --------------------------------------------------------------- Engine --
// Host-side async dependency engine (reference: include/mxnet/engine.h).
class Engine {
 public:
  explicit Engine(int n_workers = 4, int io_workers = 1) {
    Check(MXTPUEngineCreate(n_workers, io_workers, &handle_));
  }
  ~Engine() {
    if (handle_) MXTPUEngineFree(handle_);
  }
  Engine(const Engine&) = delete;
  Engine& operator=(const Engine&) = delete;

  uint64_t NewVariable() {
    uint64_t v = 0;
    Check(MXTPUEngineNewVar(handle_, &v));
    return v;
  }
  void DeleteVariable(uint64_t var) { Check(MXTPUEngineDelVar(handle_, var)); }
  // fn runs on a worker thread; nonzero return marks the op failed and the
  // error propagates to the next WaitForVar on its mutated vars.
  uint64_t Push(MXTPUEngineOpFn fn, void* ctx,
                const std::vector<uint64_t>& const_vars,
                const std::vector<uint64_t>& mutable_vars,
                const std::string& name = "", int property = 0) {
    uint64_t op_id = 0;
    Check(MXTPUEnginePush(handle_, fn, ctx, const_vars.data(),
                          static_cast<int>(const_vars.size()),
                          mutable_vars.data(),
                          static_cast<int>(mutable_vars.size()), property,
                          name.c_str(), &op_id));
    return op_id;
  }
  void OnComplete(uint64_t op_id) {
    Check(MXTPUEngineOnComplete(handle_, op_id));
  }
  void WaitForVar(uint64_t var) { Check(MXTPUEngineWaitForVar(handle_, var)); }
  void WaitAll() { Check(MXTPUEngineWaitAll(handle_)); }

 private:
  void* handle_ = nullptr;
};

// -------------------------------------------------------------- RecordIO --
class RecordReader {
 public:
  explicit RecordReader(const std::string& path, uint64_t chunk = 1 << 20,
                        int part = 0, int nparts = 1) {
    Check(MXTPURecordReaderCreate(path.c_str(), chunk, part, nparts,
                                  &handle_));
  }
  ~RecordReader() {
    if (handle_) MXTPURecordReaderFree(handle_);
  }
  RecordReader(const RecordReader&) = delete;
  RecordReader& operator=(const RecordReader&) = delete;

  // False at end of stream; the view is valid until the next call.
  bool Next(std::string* out) {
    const uint8_t* data = nullptr;
    uint32_t size = 0;
    Check(MXTPURecordReaderNext(handle_, &data, &size));
    if (!data) return false;
    out->assign(reinterpret_cast<const char*>(data), size);
    return true;
  }
  void Reset() { Check(MXTPURecordReaderReset(handle_)); }

 private:
  void* handle_ = nullptr;
};

class RecordWriter {
 public:
  explicit RecordWriter(const std::string& path) {
    Check(MXTPURecordWriterCreate(path.c_str(), &handle_));
  }
  ~RecordWriter() {
    if (handle_) MXTPURecordWriterFree(handle_);
  }
  RecordWriter(const RecordWriter&) = delete;
  RecordWriter& operator=(const RecordWriter&) = delete;

  uint64_t Write(const std::string& record) {
    uint64_t pos = 0;
    Check(MXTPURecordWriterWrite(
        handle_, reinterpret_cast<const uint8_t*>(record.data()),
        static_cast<uint32_t>(record.size()), &pos));
    return pos;
  }

 private:
  void* handle_ = nullptr;
};

}  // namespace cpp
}  // namespace mxtpu

#endif  // MXTPU_CPP_MXTPU_HPP_
