// Train linear regression from pure C++ — no Python source in the app.
//
// Reference: cpp-package/example/{lenet,mlp}.cpp train loops over
// mxnet-cpp (Symbol::SimpleBind, Executor::Forward/Backward, per-param
// sgd_update).  Same idioms here over the mxtpu tensor C ABI: build the
// graph (Variable → FullyConnected → LinearRegressionOutput), simple-
// bind, stream synthetic batches, update weights with the sgd_update
// operator, and require the loss to collapse.
//
// Usage: train_cpp (MXTPU_PYTHONPATH must resolve mxnet_tpu + jax for
// the embedded interpreter; see include/mxtpu-cpp/mxtpu.hpp).
#include <cmath>
#include <cstdio>
#include <map>
#include <random>
#include <string>
#include <vector>

#include "mxtpu-cpp/mxtpu.hpp"

using mxtpu::cpp::Context;
using mxtpu::cpp::Executor;
using mxtpu::cpp::NDArray;
using mxtpu::cpp::Op;
using mxtpu::cpp::Symbol;

int main() {
  try {
    const int kBatch = 16, kFeat = 4, kSteps = 60;
    // ground truth: y = x . (1, -2, 3, 0.5) + 0.25
    const std::vector<float> w_true = {1.f, -2.f, 3.f, 0.5f};
    const float b_true = 0.25f;

    Symbol data = Symbol::Variable("data");
    Symbol label = Symbol::Variable("label");
    Symbol fc = Symbol::CreateOp("FullyConnected", "fc",
                                 {{"data", &data}},
                                 {{"num_hidden", "1"}});
    Symbol net = Symbol::CreateOp("LinearRegressionOutput", "lro",
                                  {{"data", &fc}, {"label", &label}}, {});

    std::vector<std::string> args = net.ListArguments();
    // expected order: data, fc weight, fc bias, label
    if (args.size() != 4) {
      fprintf(stderr, "unexpected arg count %zu\n", args.size());
      return 1;
    }

    Context ctx;
    // gradients only where the optimizer needs them; pure inputs stay
    // gradient-free
    Executor ex = net.SimpleBind(
        ctx, {{"data", {kBatch, kFeat}}, {"label", {kBatch}}},
        {{"data", "null"}, {"label", "null"}, {"*", "write"}});

    std::mt19937 rng(7);
    std::normal_distribution<float> dist(0.f, 1.f);
    Op sgd("sgd_update");

    float first_loss = -1.f, last_loss = -1.f;
    for (int step = 0; step < kSteps; ++step) {
      std::vector<float> x(kBatch * kFeat), y(kBatch);
      for (int i = 0; i < kBatch; ++i) {
        float acc = b_true;
        for (int j = 0; j < kFeat; ++j) {
          x[i * kFeat + j] = dist(rng);
          acc += x[i * kFeat + j] * w_true[j];
        }
        y[i] = acc;
      }
      ex.arg_arrays[0].SyncCopyFromCPU(x);
      ex.arg_arrays[3].SyncCopyFromCPU(y);

      ex.Forward(true);
      ex.Backward();

      // per-parameter sgd (weight = arg 1, bias = arg 2)
      for (int p = 1; p <= 2; ++p) {
        sgd.Invoke({&ex.arg_arrays[p], &ex.grad_arrays[p]},
                   {&ex.arg_arrays[p]}, {{"lr", "0.1"}, {"wd", "0.0"}});
      }

      std::vector<float> pred = ex.Outputs()[0].SyncCopyToCPU();
      float loss = 0.f;
      for (int i = 0; i < kBatch; ++i)
        loss += (pred[i] - y[i]) * (pred[i] - y[i]);
      loss /= kBatch;
      if (step == 0) first_loss = loss;
      last_loss = loss;
    }

    std::vector<float> w = ex.arg_arrays[1].SyncCopyToCPU();
    printf("first loss %.4f -> last loss %.6f\n", first_loss, last_loss);
    printf("learned w: %.3f %.3f %.3f %.3f (true 1 -2 3 0.5)\n", w[0], w[1],
           w[2], w[3]);
    if (!(last_loss < first_loss * 0.05f) || !(last_loss < 0.05f)) {
      fprintf(stderr, "loss did not collapse\n");
      return 1;
    }
    for (int j = 0; j < kFeat; ++j) {
      if (std::fabs(w[j] - w_true[j]) > 0.15f) {
        fprintf(stderr, "w[%d]=%.3f off from %.3f\n", j, w[j], w_true[j]);
        return 1;
      }
    }
    printf("trained in pure C++: PASS\n");
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "EXCEPTION: %s\n", e.what());
    return 1;
  }
}
