// C++ inference example over mxtpu-cpp (reference: cpp-package/example/
// inference/).  Loads an exported model, runs a batch, prints outputs,
// then reshapes to a new batch size.
//
//   g++ -std=c++17 predict_cpp.cc -I../include -L../../mxnet_tpu/native \
//       -lmxtpu -Wl,-rpath,../../mxnet_tpu/native -o predict_cpp
//   MXTPU_PYTHONPATH=<repo>:<site-packages...> ./predict_cpp \
//       model-symbol.json model-0000.params
#include <fstream>
#include <iostream>
#include <sstream>

#include "mxtpu-cpp/mxtpu.hpp"

static std::string slurp(const char* path) {
  std::ifstream f(path, std::ios::binary);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

int main(int argc, char** argv) {
  if (argc != 3) {
    std::cerr << "usage: " << argv[0] << " <symbol.json> <params>\n";
    return 2;
  }
  try {
    mxtpu::cpp::Predictor pred(slurp(argv[1]), slurp(argv[2]),
                               {{"data", {2, 3}}});
    std::vector<float> input{0.1f, 0.2f, 0.3f, 0.4f, 0.5f, 0.6f};
    pred.SetInput("data", input);
    pred.Forward();
    for (float v : pred.GetOutput(0)) std::cout << v << " ";
    std::cout << "\n";

    auto big = pred.Reshape({{"data", {4, 3}}});
    std::vector<float> input2(12, 0.5f);
    big.SetInput("data", input2);
    big.Forward();
    std::cout << "reshaped output elements: " << big.GetOutput(0).size()
              << "\n";
  } catch (const mxtpu::cpp::Error& e) {
    std::cerr << "mxtpu error: " << e.what() << "\n";
    return 1;
  }
  return 0;
}
